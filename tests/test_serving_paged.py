"""Paged-KV serving plane (ISSUE 11): the block-pool /generate decoder.

Contracts carried onto the paged pool from the fixed-slot one
(tests/test_serving.py + tests/test_serving_resilience.py):

  * request independence — a sequence's greedy tokens are byte-invariant
    to pool co-residents, across block eviction, prefix SHARING, and
    preemption-by-recompute (the serving twin of distributed==serial);
  * crash eviction — a crashed admission fails only its own future and
    returns its blocks to the free list (PR 8 semantics).

New contracts this plane introduces: prefix-cache hits on shared
prompts, per-token streaming callbacks in emission order, SLO-class
admission (priority order, shed-youngest-of-lowest, unknown class is a
400-class ClientRequestError), preemption recovery exactness (a
preempted-and-re-admitted sequence re-consumes its window and replays
NOTHING), and HBM-budgeted arena sizing (ops/memory.kv_arena_blocks).

Reference anchor: the reference serves one record per route callback
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java) — block-pool KV
scheduling has no reference twin; provenance is the vLLM/Orca pair
cited in serving/paged.py's module docstring.
"""

import json
import os
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.resilience import (
    InjectedServingFault,
    ServingChaos,
    ServingChaosConfig,
)
from deeplearning4j_tpu.serving import QueueFullError, ServingEngine
from deeplearning4j_tpu.serving.resilience import ClientRequestError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


def _post(url, path, payload, timeout=120):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# request independence on the paged pool
# ---------------------------------------------------------------------------


class TestPagedIndependence:
    def test_solo_equals_fixed_slot_baseline(self):
        """The paged tick (write-then-gather through a block table) is
        the same arithmetic as the fixed-slot pool: greedy tokens are
        byte-identical between the two decoders."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d0 = ContinuousDecoder(lm, slots=2)
        try:
            base = d0.generate(np.asarray([[1, 5, 2, 9]]), 6,
                               temperature=0.0)[0]
        finally:
            d0.stop()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            solo = d.generate(np.asarray([[1, 5, 2, 9]]), 6,
                              temperature=0.0)[0]
        finally:
            d.stop()
        np.testing.assert_array_equal(base, solo)

    def test_coscheduled_with_prefix_sharing_equals_solo(self):
        """Greedy tokens are invariant to co-residents EVEN WHEN the
        co-resident physically shares prefix blocks (the shared blocks
        are read-only to both: write tables point the hit entries at
        trash), and the share registers as a prefix-cache hit."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        shared = [2, 4, 6, 8, 10, 12, 14, 16, 3, 5]  # > one 8-token block
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            solo_a = d.generate(np.asarray([shared + [7]]), 5,
                                temperature=0.0)[0]
            solo_b = d.generate(np.asarray([shared + [9]]), 5,
                                temperature=0.0)[0]
            before = d.stats.prefix_hits
            f1 = d.submit(shared + [7], 5, temperature=0.0)
            f2 = d.submit(shared + [9], 5, temperature=0.0)
            f3 = d.submit([3, 3, 4], 8, temperature=0.0)
            np.testing.assert_array_equal(solo_a, f1.result(timeout=120))
            np.testing.assert_array_equal(solo_b, f2.result(timeout=120))
            f3.result(timeout=120)
            assert d.stats.prefix_hits > before
        finally:
            d.stop()

    def test_blocks_return_to_free_list(self):
        """After every request completes, only prefix-cache holdings
        remain allocated; a second wave reuses the freed blocks."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            for _ in range(2):
                d.generate(np.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9]]),
                           6, temperature=0.0)
                cap = d.kv_capacity()
                assert cap["blocks_in_use"] == cap["prefix_blocks_cached"]
                assert cap["tokens_in_use"] == 0
        finally:
            d.stop()

    def test_preemption_recovery_is_exact(self):
        """A block-starved arena preempts the youngest admission and
        re-admits it later by re-consuming prompt+generated — the final
        tokens are byte-identical to an uninterrupted run (recompute,
        never resample: the live PRNG key rides the requeue)."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d0 = ContinuousDecoder(lm, slots=1)
        try:
            bases = [d0.generate(np.asarray([p]), 20, temperature=0.0)[0]
                     for p in ([2, 4, 6], [1, 1, 1, 1], [9, 8, 7])]
        finally:
            d0.stop()
        # 7 blocks * 8 tokens cannot hold three 23/24-token sequences
        # at once: growth must preempt
        d = PagedDecoder(lm, block_tokens=8, n_blocks=7)
        try:
            futs = [d.submit([2, 4, 6], 20, temperature=0.0),
                    d.submit([1, 1, 1, 1], 20, temperature=0.0),
                    d.submit([9, 8, 7], 20, temperature=0.0)]
            outs = [f.result(timeout=240) for f in futs]
            assert d.stats.preemptions >= 1
        finally:
            d.stop()
        for base, out in zip(bases, outs):
            np.testing.assert_array_equal(base, out)

    def test_seed_determinism_under_pool(self):
        """Sampling is a function of the request's own seed, not of
        block-pool scheduling: same seed twice -> same tokens."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            a = d.generate(np.asarray([[4, 4, 4]]), 5, temperature=0.8,
                           seed=7)[0]
            b = d.generate(np.asarray([[4, 4, 4]]), 5, temperature=0.8,
                           seed=7)[0]
        finally:
            d.stop()
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# crash eviction (PR 8 semantics on the paged pool)
# ---------------------------------------------------------------------------


class TestPagedCrashEviction:
    def test_crashed_admission_frees_blocks_and_spares_coresidents(self):
        """Admission k crashes: ONLY its future fails, its blocks go
        back to the free list, and a co-resident's greedy tokens equal
        its solo baseline."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        chaos = ServingChaos(ServingChaosConfig(admit_raise_at=3))
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16, chaos=chaos)
        try:
            prompt = [1, 5, 2, 9]
            solo = d.generate(np.asarray([prompt]), 8, temperature=0.0)[0]
            long_fut = d.submit(prompt, 8, temperature=0.0)
            time.sleep(0.05)  # let admission 2 land before the crasher
            crash_fut = d.submit([3, 3, 4], 6, temperature=0.0)
            with pytest.raises(InjectedServingFault):
                crash_fut.result(timeout=60)
            np.testing.assert_array_equal(solo,
                                          long_fut.result(timeout=120))
            assert d.stats.slot_crashes == 1
            cap = d.kv_capacity()
            assert cap["blocks_in_use"] == cap["prefix_blocks_cached"]
            # the pool is still alive for fresh traffic
            again = d.generate(np.asarray([prompt]), 8, temperature=0.0)[0]
            np.testing.assert_array_equal(solo, again)
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# SLO classes
# ---------------------------------------------------------------------------


class TestSLOClasses:
    def test_parse_slo_classes(self):
        from deeplearning4j_tpu.serving.slo import parse_slo_classes

        classes = parse_slo_classes("interactive:5,batch:60")
        assert [c.name for c in classes] == ["interactive", "batch"]
        # priority 0 is the HIGHEST (spec order)
        assert classes[0].priority < classes[1].priority
        assert classes[0].deadline_s == 5.0
        for bad in ("interactive", "a:1,a:2", "a:0", "a:-3", "a:x"):
            with pytest.raises(ValueError):
                parse_slo_classes(bad)

    def test_unknown_class_is_client_error(self):
        from deeplearning4j_tpu.serving.paged import PagedDecoder
        from deeplearning4j_tpu.serving.slo import parse_slo_classes

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16,
                         slo_classes=parse_slo_classes("rt:5,bulk:60"))
        try:
            with pytest.raises(ClientRequestError):
                d.submit([1, 2, 3], 4, slo="nope")
        finally:
            d.stop()

    def test_full_queue_sheds_youngest_of_lowest_class(self):
        """Past queue_cap a higher-priority submit sheds the youngest
        pending request of the lowest class strictly below it; a
        low-class submit with nothing to shed gets the 429."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder
        from deeplearning4j_tpu.serving.slo import parse_slo_classes

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16, lanes=1,
                         slo_classes=parse_slo_classes("rt:30,bulk:30"),
                         queue_cap=2)
        try:
            # the hog takes the single lane; its on_token throttle keeps
            # the lane busy long enough for the queue choreography below
            # to be race-free on a loaded host
            hog = d.submit([2, 4, 6], 20, temperature=0.0,
                           on_token=lambda t: time.sleep(0.02))
            time.sleep(0.1)
            old = d.submit([1, 2], 3, temperature=0.0, slo="bulk")
            young = d.submit([3, 4], 3, temperature=0.0, slo="bulk")
            # queue full: the rt submit sheds the YOUNGEST bulk request
            kept = d.submit([5, 6], 3, temperature=0.0, slo="rt")
            with pytest.raises(QueueFullError):
                young.result(timeout=5)
            assert d.stats.shed_by_class.get("bulk") == 1
            # queue full again, and a bulk arrival outranks nobody: 429
            with pytest.raises(QueueFullError):
                d.submit([7, 8], 3, temperature=0.0, slo="bulk")
            assert hog.result(timeout=120).shape == (20,)
            assert old.result(timeout=120).shape == (3,)
            assert kept.result(timeout=120).shape == (3,)
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# streaming
# ---------------------------------------------------------------------------


class TestStreaming:
    def test_on_token_streams_in_emission_order(self):
        """The callback sees every token, in order, and all of them
        BEFORE the future resolves (a consumer observing a done future
        may drain-then-stop without losing tokens)."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            streamed = []
            fut = d.submit([1, 5, 2, 9], 6, temperature=0.0,
                           on_token=streamed.append)
            out = fut.result(timeout=120)
            assert streamed == list(out)
        finally:
            d.stop()

    def test_http_stream_matches_nonstream(self):
        """POST /generate with stream=true chunks NDJSON token events
        and a final done record whose tokens equal the non-streaming
        response for the same request."""
        lm = tiny_lm()
        eng = ServingEngine(model=lm, kv_block=8, kv_blocks=16).start()
        try:
            plain = _post(eng.url, "/generate",
                          {"tokens": [1, 5, 2, 9], "n_new": 6,
                           "temperature": 0.0})["tokens"][0]
            req = urllib.request.Request(
                eng.url + "/generate",
                data=json.dumps({"tokens": [1, 5, 2, 9], "n_new": 6,
                                 "temperature": 0.0,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                assert resp.headers.get("Content-Type") == \
                    "application/x-ndjson"
                events = [json.loads(ln) for ln in resp.read().splitlines()
                          if ln.strip()]
            toks = [e["token"] for e in events if "token" in e]
            done = [e for e in events if e.get("done")]
            assert toks == plain
            assert done and done[0]["tokens"] == plain
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# engine integration: default paged, KV_BLOCK=0 fallback, /models report
# ---------------------------------------------------------------------------


class TestEngineIntegration:
    def test_paged_default_and_fixed_slot_fallback_agree(self):
        """kv_block>0 (the default) serves /generate from the paged
        pool; kv_block=0 falls back to the fixed-slot decoder; both
        return identical greedy tokens and report their scheme (and
        capacity in tokens) at /models."""
        lm = tiny_lm()
        eng = ServingEngine(model=lm, kv_block=8, kv_blocks=16).start()
        try:
            paged = _post(eng.url, "/generate",
                          {"tokens": [1, 5, 2, 9], "n_new": 6,
                           "temperature": 0.0})["tokens"][0]
            kv = _get(eng.url, "/models")["kv"]["default@v1"]
            assert kv["scheme"] == "paged"
            assert kv["capacity_tokens"] == 16 * 8
        finally:
            eng.stop()
        eng = ServingEngine(model=lm, kv_block=0).start()
        try:
            fixed = _post(eng.url, "/generate",
                          {"tokens": [1, 5, 2, 9], "n_new": 6,
                           "temperature": 0.0})["tokens"][0]
            kv = _get(eng.url, "/models")["kv"]["default@v1"]
            assert kv["scheme"] == "fixed-slot"
            assert kv["capacity_tokens"] == kv["slots"] * 32
        finally:
            eng.stop()
        assert paged == fixed

    def test_http_slo_routing_and_unknown_class_400(self):
        lm = tiny_lm()
        eng = ServingEngine(model=lm, kv_block=8, kv_blocks=16,
                            slo_classes="interactive:30,batch:120").start()
        try:
            out = _post(eng.url, "/generate",
                        {"tokens": [1, 5, 2, 9], "n_new": 3,
                         "temperature": 0.0, "slo": "interactive"})
            assert len(out["tokens"][0]) == 3
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(eng.url, "/generate",
                      {"tokens": [1, 2], "n_new": 2, "slo": "nope"})
            assert exc.value.code == 400
        finally:
            eng.stop()

    def test_bad_slo_spec_fails_at_construction(self):
        with pytest.raises(ValueError):
            ServingEngine(model=tiny_lm(), slo_classes="oops")


# ---------------------------------------------------------------------------
# arena sizing (the fixed-pool over-allocation fix)
# ---------------------------------------------------------------------------


class TestArenaSizing:
    def test_kv_block_bytes_closed_form(self):
        from deeplearning4j_tpu.models.transformer import TransformerConfig
        from deeplearning4j_tpu.ops.memory import kv_block_bytes

        cfg = TransformerConfig(vocab_size=29, d_model=16, n_layers=2,
                                n_heads=2, d_ff=32, max_len=32)
        # k+v, per layer: bt * H * hd elements
        itemsize = np.dtype(cfg.compute_dtype).itemsize
        assert kv_block_bytes(cfg, 8) == 2 * 2 * 8 * 16 * itemsize

    def test_kv_arena_blocks_respects_budget_and_floor(self):
        from deeplearning4j_tpu.models.transformer import TransformerConfig
        from deeplearning4j_tpu.ops.memory import (
            kv_arena_blocks,
            kv_block_bytes,
        )

        cfg = TransformerConfig(vocab_size=29, d_model=16, n_layers=2,
                                n_heads=2, d_ff=32, max_len=32)
        per = kv_block_bytes(cfg, 8)
        # budget for exactly 10 blocks at kv_fraction=1.0
        gb = 10 * per / 2**30
        assert kv_arena_blocks(cfg, 8, hbm_gb=gb, kv_fraction=1.0) == 10
        # a starvation budget still floors at one max_len sequence + 1
        floor = cfg.max_len // 8 + 1
        assert kv_arena_blocks(cfg, 8, hbm_gb=1e-9,
                               kv_fraction=1.0) == floor

    def test_arena_too_small_for_one_sequence_raises(self):
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        with pytest.raises(ValueError):
            PagedDecoder(tiny_lm(), block_tokens=8, n_blocks=4)

    def test_block_tokens_auto_divides_max_len(self):
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=12, n_blocks=40)
        try:
            assert lm.cfg.max_len % d.block_tokens == 0
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# ledger + bench registration
# ---------------------------------------------------------------------------


class TestPlumbing:
    def test_new_ledger_fields_in_snapshot(self):
        from deeplearning4j_tpu.serving.telemetry import ServingStats

        s = ServingStats()
        s.set_kv_blocks(3, 16)
        s.record_prefix(1, 2)
        s.record_preemption()
        s.record_shed("bulk")
        snap = s.snapshot()
        assert snap["kv_blocks_in_use"] == 3
        assert snap["kv_blocks_total"] == 16
        assert snap["prefix_hits"] == 1 and snap["prefix_lookups"] == 2
        assert snap["preemptions"] == 1
        assert snap["shed_by_class"] == {"bulk": 1}

    def test_serving_decode_leg_registered(self):
        """bench.py defines the serving_decode leg, bench_state expects
        it, and it is pinned CPU-only (the leg is a scheduler benchmark,
        not a chip benchmark)."""
        from scripts.bench_state import EXPECTED

        assert "serving_decode" in EXPECTED
        src = open(os.path.join(REPO, "bench.py")).read()
        legs = set(re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M))
        assert "serving_decode" in legs
        cpu_only = re.search(r"_CPU_ONLY_LEGS\s*=\s*\{([^}]*)\}", src)
        assert cpu_only and "serving_decode" in cpu_only.group(1)
