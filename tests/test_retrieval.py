"""Embedding & retrieval serving plane (deeplearning4j_tpu/retrieval/)
— ISSUE 17.

Quick-tier contracts:

  (a) /embed through the DynamicBatcher is BYTE-identical to the direct
      feed_forward slice on the same rows, and the bucket-ladder pad
      rows are inert (a 5-row request padded to bucket 8 equals the
      5 per-row requests) — the serving batcher==direct convention
      extended to the embedding surface.
  (b) ExactIndex matches a numpy full-scan oracle exactly; IVF recall@k
      is MEASURED against that oracle on the same snapshot and clears
      the 0.95 bar on a clustered corpus (never assumed).
  (c) a generation publish racing live /search traffic fails ZERO
      admitted requests, and every answer is coherent (ids from some
      published generation, never a torn mix) — the online/promote
      atomic-swap contract re-proved for indexes.
  (d) a latched DriftMonitor alarm VETOES a publish (generation
      unmoved, PublishVetoed, veto counted); force=True overrides.

Plus satellites: the DL4J_TPU_EMBED_*/DL4J_TPU_ANN_* knob registration,
the retrieval_stats ledger registration convention, /models AOT
embed/index reporting, and StreamSource-fed online mutation windows.

Reference anchor: the reference's nlp plane answers wordsNearest with a
host full scan (InMemoryLookupTable.java:73 / BasicModelUtils role);
the /embed + /search serving surface is beyond-reference (PARITY.md).
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.online import DriftMonitor, StreamSource
from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.retrieval import (
    ExactIndex,
    IndexFullError,
    IVFIndex,
    LookupEmbedding,
    PublishVetoed,
    VectorStore,
    measure_recall,
    resolve_adapter,
)
from deeplearning4j_tpu.serving.engine import ServingEngine


def tiny_net(seed=7, n_in=8, hidden=12, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).list()
            .layer(0, DenseLayer(n_in=n_in, n_out=hidden,
                                 activation="relu"))
            .layer(1, OutputLayer(n_in=hidden, n_out=n_out,
                                  activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


def clustered_corpus(rng, n=512, dim=16, clusters=16, spread=0.05):
    """A corpus with real cluster structure — the regime IVF probing is
    FOR (uniform random vectors would make any recall bar meaningless)."""
    centers = rng.normal(size=(clusters, dim)).astype(np.float32)
    assign = rng.integers(0, clusters, size=n)
    pts = centers[assign] + spread * rng.normal(size=(n, dim))
    return pts.astype(np.float32)


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.load(resp)


@pytest.fixture
def engine():
    net = tiny_net()
    eng = ServingEngine(model=net, input_shape=(8,)).start()
    yield eng, net
    eng.stop()


class TestEmbedEquivalence:
    def test_batcher_equals_direct_byte_identical(self, engine):
        """Contract (a): the batcher path answers the exact bytes the
        direct feed_forward hidden-layer slice produces."""
        eng, net = engine
        rng = np.random.default_rng(0)
        x = rng.normal(size=(5, 8)).astype(np.float32)
        via_batcher = eng.embed(x)
        acts = net.feed_forward(x, train=False)
        direct = np.asarray(acts[-2], np.float32).reshape(5, -1)
        assert via_batcher.dtype == direct.dtype
        assert np.array_equal(via_batcher, direct)

    def test_pad_rows_inert(self, engine):
        """Contract (a): a 5-row request (padded to bucket 8 inside the
        dispatch) == the same 5 rows requested one at a time."""
        eng, _ = engine
        rng = np.random.default_rng(1)
        x = rng.normal(size=(5, 8)).astype(np.float32)
        batched = eng.embed(x)
        per_row = np.concatenate([eng.embed(x[i:i + 1]) for i in range(5)])
        assert np.array_equal(batched, per_row)

    def test_concurrent_requests_coalesce_byte_equal(self, engine):
        """Concurrent single-row /embed requests ride one coalesced
        dispatch; each caller still gets its own exact slice."""
        eng, net = engine
        rng = np.random.default_rng(2)
        x = rng.normal(size=(8, 8)).astype(np.float32)
        want = np.asarray(net.feed_forward(x, train=False)[-2],
                          np.float32).reshape(8, -1)
        out = [None] * 8
        errs = []

        def one(i):
            try:
                out[i] = eng.embed(x[i:i + 1])
            except Exception as e:  # noqa: BLE001 — test harness
                errs.append(e)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert np.array_equal(np.concatenate(out), want)

    def test_http_embed_record_and_batch(self, engine):
        eng, net = engine
        rng = np.random.default_rng(3)
        x = rng.normal(size=(3, 8)).astype(np.float32)
        want = np.asarray(net.feed_forward(x, train=False)[-2],
                          np.float32).reshape(3, -1)
        r = _post(eng.port, "/embed", {"batch": x.tolist()})
        assert r["dim"] == want.shape[1]
        assert np.array_equal(
            np.asarray(r["embeddings"], np.float32), want)
        r1 = _post(eng.port, "/embed", {"record": x[0].tolist()})
        assert np.array_equal(np.asarray(r1["embedding"], np.float32),
                              want[0])

    def test_embed_counters(self, engine):
        eng, _ = engine
        eng.embed(np.zeros((4, 8), np.float32))
        snap = eng.retrieval_stats.snapshot()
        assert snap["embed_requests"] >= 1
        assert snap["embed_rows"] >= 4


class TestAdapters:
    def test_lookup_adapter_matches_syn0(self):
        class Table:
            vector_length = 6
            syn0 = np.arange(60, dtype=np.float32).reshape(10, 6)

            def vectors(self, idx):
                return self.syn0[np.asarray(idx, np.int64)]

        ad = LookupEmbedding(Table())
        assert ad.dim == 6
        got = ad(np.asarray([[2], [7]]))
        assert np.array_equal(got, Table.syn0[[2, 7]])

    def test_feedforward_aot_dim_without_execution(self):
        net = tiny_net()
        ad = resolve_adapter(net, input_shape=(8,))
        # dim known BEFORE any __call__ (jax.eval_shape — the /models
        # tunnel-free contract)
        assert ad.dim == 12

    def test_unsupported_model_raises(self):
        with pytest.raises(TypeError):
            resolve_adapter(object())


class TestIndexes:
    def test_exact_matches_numpy_oracle(self):
        rng = np.random.default_rng(10)
        vecs = rng.normal(size=(100, 16)).astype(np.float32)
        store = VectorStore(16, capacity=128, kind="exact", name="ex")
        store.upsert(np.arange(100), vecs)
        store.publish()
        q = rng.normal(size=(7, 16)).astype(np.float32)
        ids, scores = store.search(q, k=5)
        vn = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        oracle = np.argsort(-(qn @ vn.T), axis=1)[:, :5]
        assert np.array_equal(ids, oracle)

    def test_ivf_recall_bar_measured(self):
        """Contract (b): recall@10 >= 0.95 on a clustered corpus,
        measured against the exact oracle on the SAME snapshot."""
        rng = np.random.default_rng(11)
        vecs = clustered_corpus(rng, n=512, dim=16, clusters=16)
        store = VectorStore(16, capacity=1024, kind="ivf", clusters=16,
                            nprobe=6, name="ivf")
        store.upsert(np.arange(512), vecs)
        store.publish()
        assert store.snapshot.centroids is not None
        q = clustered_corpus(rng, n=64, dim=16, clusters=16)
        recall = store.probe_recall(q, k=10)
        assert recall >= 0.95
        assert store.retrieval_stats.snapshot()["last_recall"] == recall

    def test_ivf_below_min_rows_serves_exact(self):
        store = VectorStore(8, capacity=64, kind="ivf", min_ivf_rows=32,
                            name="small")
        rng = np.random.default_rng(12)
        store.upsert(np.arange(4), rng.normal(size=(4, 8)))
        store.publish()
        assert store.snapshot.centroids is None  # exact fallback
        ids, _ = store.search(rng.normal(size=(1, 8)), k=2)
        assert set(ids[0]) <= set(range(4))

    def test_fewer_live_rows_than_k(self):
        store = VectorStore(8, capacity=16, kind="exact", name="few")
        store.upsert([5, 9], np.eye(8, dtype=np.float32)[:2])
        store.publish()
        ids, scores = store.search(np.eye(8, dtype=np.float32)[:1], k=4)
        assert ids[0][0] == 5
        # k clamps to the padded arena; entries past the 2 live rows
        # surface as id -1, never a garbage slot
        assert set(ids[0]) == {5, 9, -1}

    def test_delete_never_returned(self):
        rng = np.random.default_rng(13)
        vecs = rng.normal(size=(40, 8)).astype(np.float32)
        store = VectorStore(8, capacity=64, kind="exact", name="del")
        store.upsert(np.arange(40), vecs)
        store.publish()
        store.delete(np.arange(0, 40, 2))
        store.publish()
        ids, _ = store.search(vecs, k=5)
        assert not np.any(ids % 2 == 0)  # every even id was deleted

    def test_upsert_replaces_in_place(self):
        store = VectorStore(4, capacity=8, kind="exact", name="rep")
        store.upsert([1], [[1, 0, 0, 0]])
        store.upsert([1], [[0, 1, 0, 0]])  # same id: replace, not grow
        store.publish()
        assert store.rows == 1
        ids, _ = store.search(np.asarray([[0, 1, 0, 0]], np.float32), k=1)
        assert ids[0][0] == 1

    def test_capacity_full_raises(self):
        store = VectorStore(4, capacity=2, kind="exact", name="full")
        store.upsert([0, 1], np.eye(4, dtype=np.float32)[:2])
        with pytest.raises(IndexFullError):
            store.upsert([2], np.eye(4, dtype=np.float32)[2:3])

    def test_measure_recall_direct(self):
        rng = np.random.default_rng(14)
        vecs = clustered_corpus(rng, n=256, dim=8, clusters=8)
        store = VectorStore(8, capacity=512, kind="ivf", clusters=8,
                            nprobe=8, name="mr")
        store.upsert(np.arange(256), vecs)
        store.publish()
        # nprobe == clusters probes EVERYTHING: recall is exactly 1.0
        ivf = IVFIndex(clusters=8, nprobe=8)
        assert measure_recall(store.snapshot, ivf,
                              vecs[:16], k=10) == 1.0


class TestGenerationSwap:
    def test_zero_failed_searches_across_publishes(self):
        """Contract (c): publishes racing live search traffic fail zero
        admitted requests, and every answer maps to a coherent
        published generation."""
        rng = np.random.default_rng(20)
        dim = 8
        store = VectorStore(dim, capacity=512, kind="exact", name="swap")
        store.upsert(np.arange(32), rng.normal(size=(32, dim)))
        store.publish()
        q = rng.normal(size=(4, dim)).astype(np.float32)
        stop = threading.Event()
        errs = []
        answered = [0]

        def searcher():
            while not stop.is_set():
                try:
                    ids, scores = store.search(q, k=5)
                    assert ids.shape == (4, 5)
                    assert np.all(np.isfinite(scores[ids >= 0]))
                    answered[0] += 1
                except Exception as e:  # noqa: BLE001 — the contract
                    errs.append(e)
                    return

        threads = [threading.Thread(target=searcher) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for gen_round in range(8):
                base = 32 + gen_round * 16
                store.upsert(np.arange(base, base + 16),
                             rng.normal(size=(16, dim)))
                store.publish()
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert errs == []
        assert answered[0] > 0
        assert store.generation == 9

    def test_engine_search_across_swap(self):
        """The engine /search surface rides the same snapshot
        discipline — swaps under live HTTP traffic fail nothing."""
        net = tiny_net()
        eng = ServingEngine(model=net, input_shape=(8,)).start()
        try:
            rng = np.random.default_rng(21)
            store = VectorStore(12, capacity=256, kind="exact", name="es")
            corpus = eng.embed(rng.normal(size=(32, 8)).astype(np.float32))
            store.upsert(np.arange(32), corpus)
            store.publish()
            eng.register_index("es", store)
            q = corpus[0].tolist()
            stop = threading.Event()
            errs = []

            def client():
                while not stop.is_set():
                    try:
                        r = _post(eng.port, "/search",
                                  {"index": "es", "query": q, "k": 3})
                        assert len(r["ids"][0]) == 3
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)
                        return

            t = threading.Thread(target=client)
            t.start()
            try:
                for i in range(5):
                    store.upsert([100 + i], rng.normal(size=(1, 12)))
                    store.publish()
            finally:
                stop.set()
                t.join()
            assert errs == []
        finally:
            eng.stop()


class TestDriftVeto:
    def _drifted_monitor(self, dim=8):
        drift = DriftMonitor((np.zeros(dim), np.ones(dim)), min_rows=16)
        drift.observe(np.full((32, dim), 50.0, np.float32))  # z = 50
        assert drift.check()["alarmed"]
        return drift

    def test_veto_blocks_publish(self):
        """Contract (d): a latched alarm vetoes; generation unmoved."""
        store = VectorStore(8, capacity=64, kind="exact", name="veto")
        store.upsert(np.arange(8), np.eye(8, dtype=np.float32))
        store.publish()
        assert store.generation == 1
        store.upsert([9], [np.ones(8, np.float32)])
        drift = self._drifted_monitor()
        with pytest.raises(PublishVetoed):
            store.publish(drift=drift)
        assert store.generation == 1  # unmoved
        assert store.retrieval_stats.snapshot()["publish_vetoes"] == 1
        # the staged row is NOT lost — a forced publish lands it
        store.publish(drift=drift, force=True)
        assert store.generation == 2
        ids, _ = store.search(np.ones((1, 8), np.float32), k=1)
        assert ids[0][0] == 9

    def test_feed_once_reports_veto(self):
        store = VectorStore(8, capacity=64, kind="exact", name="feedveto")
        drift = self._drifted_monitor()
        src = StreamSource(watermark=8, idle_s=0.05)
        src.push(DataSet(np.eye(8, dtype=np.float32)[:4],
                         np.arange(4, dtype=np.float32)[:, None]))
        report = store.feed_once(src, drift=drift)
        assert report["vetoed"] and not report["published"]
        assert report["generation"] == 0
        src.close()


class TestOnlineFeed:
    def test_stream_fed_window_publishes(self):
        rng = np.random.default_rng(30)
        store = VectorStore(8, capacity=128, kind="exact", name="feed")
        src = StreamSource(watermark=16, idle_s=0.05)
        vecs = rng.normal(size=(12, 8)).astype(np.float32)
        src.push(DataSet(vecs[:8], np.arange(8, dtype=np.float32)[:, None]))
        src.push(DataSet(vecs[8:], np.arange(8, 12,
                                             dtype=np.float32)[:, None]))
        report = store.feed_once(src)
        assert report["batches"] == 2
        assert report["upserted"] == 12
        assert report["published"] and report["generation"] == 1
        # delete op rides a tuple batch
        src.push(("delete", np.arange(6)))
        report = store.feed_once(src)
        assert report["deleted"] == 6 and report["generation"] == 2
        assert store.rows == 6
        src.close()
        snap = store.retrieval_stats.snapshot()
        assert snap["feed_windows"] == 2 and snap["feed_batches"] == 3


class TestSatellites:
    def test_knobs_registered(self):
        names = envknob.knob_names()
        for knob in ("DL4J_TPU_EMBED_LAYER", "DL4J_TPU_EMBED_POOL",
                     "DL4J_TPU_ANN_ROWS", "DL4J_TPU_ANN_CLUSTERS",
                     "DL4J_TPU_ANN_NPROBE"):
            assert knob in names, f"{knob} missing from ops/env.py"

    def test_ann_rows_knob_sizes_capacity(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_ANN_ROWS", "77")
        store = VectorStore(8, name="knob")
        assert store.capacity == 77

    def test_auto_capacity_is_aot(self, monkeypatch):
        from deeplearning4j_tpu.ops import memory

        monkeypatch.setenv("DL4J_TPU_HBM_GB", "16")
        rows = memory.ann_arena_rows(64)
        assert rows >= 1024  # closed-form, no device involved
        monkeypatch.setenv("DL4J_TPU_ANN_ROWS", "0")
        store = VectorStore(64, name="auto")
        assert store.capacity == rows

    def test_models_reports_embed_and_indexes(self, engine):
        eng, _ = engine
        store = VectorStore(12, capacity=64, kind="exact", name="default")
        store.upsert([0], np.ones((1, 12), np.float32))
        store.publish()
        eng.register_index("default", store)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{eng.port}/models", timeout=30) as resp:
            m = json.load(resp)
        assert m["embed"]["default@v1"] == {"kind": "feedforward",
                                            "dim": 12}
        rep = m["indexes"]["default"]
        assert rep["rows"] == 1 and rep["capacity"] == 64
        assert rep["generation"] == 1
        assert rep["arena_bytes"] == 65 * 12 * 4

    def test_ledger_registered_with_obs(self):
        from deeplearning4j_tpu.obs import registry as obs_registry

        store = VectorStore(8, capacity=16, name="ledger")
        reg = obs_registry.default_registry()
        assert reg.ledgers(store)["retrieval_stats"] is store.retrieval_stats

    def test_search_unknown_index_is_client_error(self, engine):
        from deeplearning4j_tpu.serving.resilience import ClientRequestError

        eng, _ = engine
        with pytest.raises(ClientRequestError):
            eng.search("nope", np.zeros((1, 4), np.float32))
