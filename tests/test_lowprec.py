"""Low-precision plane (ISSUE 15): calibrated int8 serving + bf16
loss-scaled training (ops/lowprec.py + etl/calibrate.py).

Contracts:

  * int8 accuracy — QuantizedNet output stays within the
    DL4J_TPU_QUANT_MAX_DELTA gate of the f32 record on an MLP and on a
    conv net (where only the dense head quantizes — per-layer fallback);
  * fail-safe gate — a quantized record past the bar lands BROKEN
    through ModelRegistry.load's isolation and the serving default never
    moves (the PR 8 rollback primitive, applied to precision);
  * bf16 loss scaling — training reaches f32-class loss; a chaos-forced
    overflow (resilience/chaos.LowPrecChaos, config-driven never
    ambient) halves the scale and SKIPS the step (master weights
    untouched); clean streaks double the scale on schedule;
  * kill/resume — bf16 training killed at step k and resumed is
    BIT-exact vs uninterrupted (the loss-scale state rides the
    checkpoint through training_state());
  * flagships — TransformerLM carries the scale inside the opt tree
    (save/load round-trips it); the ring/pipeline paths reject the knob
    loudly instead of silently dropping it;
  * serving — DL4J_TPU_SERVE_KV_DTYPE=bf16 halves kv_block_bytes so the
    same HBM budget admits ~2x tokens, and the paged tick takes the
    gather path (kernel verdicts were measured at compute dtype).

Reference anchor: the reference's only dtype story is the global ND4J
buffer type switch (SURVEY.md, nd4j-api DataBuffer.Type) — calibration,
accuracy gating and loss scaling are beyond-parity.
"""

import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.etl.calibrate import (
    QuantCalibrator,
    QuantSpec,
    quant_spec_from_json,
)
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import env, lowprec
from deeplearning4j_tpu.resilience import (
    ChaosConfig,
    ChaosMonkey,
    CheckpointManager,
    InjectedKill,
    LowPrecChaos,
    LowPrecChaosConfig,
    ResilientTrainer,
)

ENV_BF16 = "DL4J_TPU_BF16"
ENV_SCALE = "DL4J_TPU_LOSS_SCALE"
ENV_QUANT = "DL4J_TPU_QUANT"
ENV_DELTA = "DL4J_TPU_QUANT_MAX_DELTA"
ENV_KV = "DL4J_TPU_SERVE_KV_DTYPE"

_RNG = np.random.default_rng(0)
X = _RNG.standard_normal((48, 6)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[_RNG.integers(0, 3, 48)]


def build_mln() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def build_cg() -> ComputationGraph:
    conf = (
        NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
        .updater("adam").graph_builder().add_inputs("in")
        .add_layer("d", DenseLayer(n_in=6, n_out=8, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out").build()
    )
    return ComputationGraph(conf)


def build_conv_net() -> MultiLayerNetwork:
    """Conv stack + dense head (the LeNet shape at smoke scale): only the
    head is int8-eligible, the conv/pool layers must fall back."""
    from deeplearning4j_tpu.nn.conf import ConvolutionLayer, SubsamplingLayer
    from deeplearning4j_tpu.nn.conf.preprocessors import (
        CnnToFeedForwardPreProcessor,
    )

    conf = (
        NeuralNetConfiguration.builder().seed(11).learning_rate(0.05)
        .updater("sgd").weight_init("xavier").list()
        .layer(0, ConvolutionLayer(n_in=1, n_out=3, kernel_size=(3, 3),
                                   stride=(1, 1), activation="relu"))
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                   stride=(2, 2)))
        .layer(2, OutputLayer(n_in=3 * 3 * 3, n_out=2, activation="softmax",
                              loss_function="mcxent"))
        .input_preprocessor(2, CnnToFeedForwardPreProcessor(3, 3, 3))
        .build()
    )
    return MultiLayerNetwork(conf).init(input_shape=(8, 8, 1))


def tiny_lm_cfg(**over):
    from deeplearning4j_tpu.models.transformer import TransformerConfig

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=16, learning_rate=1e-3, seed=3, use_flash=False)
    kw.update(over)
    return TransformerConfig(**kw)


def params_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def fitted_net_and_spec():
    """A briefly-trained MLP plus its calibrated QuantSpec."""
    net = build_mln().init()
    for i in range(0, 48, 8):
        net.fit(X[i:i + 8], Y[i:i + 8])
    spec = QuantCalibrator().fit(
        net, ListDataSetIterator(X, Y, batch=8)).spec(net)
    return net, spec


# ---------------------------------------------------------------------------
# knob walk: every new knob reads through the ops/env.py table
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_all_registered(self):
        for name in (ENV_QUANT, ENV_DELTA, ENV_BF16, ENV_SCALE, ENV_KV):
            assert env.is_registered(name), name

    def test_quant_mode(self, monkeypatch):
        monkeypatch.delenv(ENV_QUANT, raising=False)
        assert lowprec.quant_mode() == "auto"
        monkeypatch.setenv(ENV_QUANT, "0")
        assert lowprec.quant_mode() == "off"
        monkeypatch.setenv(ENV_QUANT, "force")
        assert lowprec.quant_mode() == "force"

    def test_loss_scale_spec(self, monkeypatch):
        monkeypatch.delenv(ENV_SCALE, raising=False)
        assert lowprec.loss_scale_config() == (32768.0, 2000)
        monkeypatch.setenv(ENV_SCALE, "1024:4")
        assert lowprec.loss_scale_config() == (1024.0, 4)
        monkeypatch.setenv(ENV_SCALE, "garbage:junk")
        assert lowprec.loss_scale_config() == (32768.0, 2000)

    def test_quant_max_delta(self, monkeypatch):
        monkeypatch.delenv(ENV_DELTA, raising=False)
        assert lowprec.quant_max_delta() == pytest.approx(0.05)
        monkeypatch.setenv(ENV_DELTA, "0.2")
        assert lowprec.quant_max_delta() == pytest.approx(0.2)

    def test_kv_dtype(self, monkeypatch):
        cfg = tiny_lm_cfg()
        monkeypatch.delenv(ENV_KV, raising=False)
        assert jnp.dtype(lowprec.kv_dtype(cfg)) == jnp.dtype(jnp.float32)
        monkeypatch.setenv(ENV_KV, "bf16")
        assert jnp.dtype(lowprec.kv_dtype(cfg)) == jnp.dtype(jnp.bfloat16)
        monkeypatch.setenv(ENV_KV, "f32")
        assert jnp.dtype(lowprec.kv_dtype(cfg)) == jnp.dtype(jnp.float32)


# ---------------------------------------------------------------------------
# int8 value contracts
# ---------------------------------------------------------------------------


class TestInt8:
    def test_quantize_weight_roundtrip(self):
        w = _RNG.standard_normal((6, 4)).astype(np.float32)
        wq, scale = lowprec.quantize_weight(w)
        assert np.asarray(wq).dtype == np.int8
        assert np.abs(np.asarray(wq)).max() <= 127
        deq = np.asarray(wq, np.float32) * np.asarray(scale)
        # per-channel symmetric scheme: worst-case error is half an LSB
        assert np.max(np.abs(deq - w)) <= float(np.asarray(scale).max())

    def test_mlp_within_gate(self):
        net, spec = fitted_net_and_spec()
        qnet = lowprec.QuantizedNet(net, spec)
        assert qnet.quantized_layers() == [0, 1]
        delta = np.max(np.abs(np.asarray(qnet.output(X))
                              - np.asarray(net.output(X))))
        assert 0.0 < delta <= lowprec.quant_max_delta()

    def test_conv_head_quantizes_rest_falls_back(self):
        net = build_conv_net()
        xs = _RNG.standard_normal((16, 8, 8, 1)).astype(np.float32)
        spec = QuantCalibrator().fit(net, xs).spec(net)
        qnet = lowprec.QuantizedNet(net, spec)
        assert qnet.quantized_layers() == [2]  # conv + pool fall back
        delta = np.max(np.abs(np.asarray(qnet.output(xs))
                              - np.asarray(net.output(xs))))
        assert delta <= lowprec.quant_max_delta()

    def test_calibrator_audit_and_gate_sample(self):
        net, spec = fitted_net_and_spec()
        assert spec.sample is not None and spec.sample.shape == (32, 6)
        assert all(s is not None and s > 0 for s in spec.act_scales)
        assert all(a["absmax"] >= a["std"] for a in spec.audit)

    def test_spec_json_roundtrip(self):
        _, spec = fitted_net_and_spec()
        back = quant_spec_from_json(spec.to_json())
        assert back.act_scales == pytest.approx(spec.act_scales)
        np.testing.assert_array_equal(back.sample, spec.sample)
        assert back.meta["layers"] == spec.meta["layers"]

    def test_quant_json_rides_the_model_zip(self, tmp_path):
        from deeplearning4j_tpu.utils.serialization import (
            ModelSerializer,
            read_quant,
        )

        net, spec = fitted_net_and_spec()
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path, quant=spec)
        with zipfile.ZipFile(path) as z:
            assert "quant.json" in z.namelist()
        back = read_quant(path)
        assert back.act_scales == pytest.approx(spec.act_scales)


# ---------------------------------------------------------------------------
# registry gate: fail-safe by construction
# ---------------------------------------------------------------------------


class TestQuantGate:
    def test_auto_pass_serves_int8(self):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        net, spec = fitted_net_and_spec()
        reg = ModelRegistry()
        rec = reg.load("m", model=net, quant=spec)
        assert rec.precision == "int8"
        assert rec.quant["verdict"] == "ok"
        assert rec.quant["delta"] <= rec.quant["max_delta"]
        assert rec.quant["layers"] == [0, 1]
        desc = [d for d in reg.describe() if d["version"] == rec.version][0]
        assert desc["precision"] == "int8" and desc["quant"]["verdict"] == "ok"

    def test_gate_failure_lands_broken_default_unmoved(self, monkeypatch):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        net, spec = fitted_net_and_spec()
        reg = ModelRegistry()
        reg.load("m", model=net)
        reg.serve("m", 1)
        # an impossible bar: any real rounding error trips the gate
        monkeypatch.setenv(ENV_DELTA, "1e-12")
        with pytest.raises(lowprec.QuantGateError):
            reg.load("m", model=build_mln().init(), quant=spec)
        default = reg.default()
        assert (default.name, default.version) == ("m", 1)
        assert default.precision == "f32"
        broken = [d for d in reg.describe() if d["version"] == 2]
        assert broken and broken[0]["state"] == "broken"
        assert "gate failed" in broken[0]["error"]

    def test_off_serves_f32(self, monkeypatch):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        net, spec = fitted_net_and_spec()
        monkeypatch.setenv(ENV_QUANT, "0")
        rec = ModelRegistry().load("m", model=net, quant=spec)
        assert rec.precision == "f32" and rec.quant is None

    def test_force_past_bar_is_audited(self, monkeypatch):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        net, spec = fitted_net_and_spec()
        monkeypatch.setenv(ENV_DELTA, "1e-12")
        monkeypatch.setenv(ENV_QUANT, "force")
        rec = ModelRegistry().load("m", model=net, quant=spec)
        assert rec.precision == "int8"
        assert rec.quant["verdict"] == "forced"
        assert rec.quant["delta"] > 1e-12  # measured and reported, not hidden

    def test_sampleless_spec_is_ungated_f32(self):
        from deeplearning4j_tpu.serving.registry import ModelRegistry

        net, spec = fitted_net_and_spec()
        blind = QuantSpec(spec.act_scales, sample=None)
        rec = ModelRegistry().load("m", model=net, quant=blind)
        assert rec.precision == "f32"
        assert rec.quant["verdict"] == "ungated"

    def test_zip_quant_autopickup(self, tmp_path):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        net, spec = fitted_net_and_spec()
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path, quant=spec)
        rec = ModelRegistry().load("m", model_path=path)
        assert rec.precision == "int8" and rec.quant["verdict"] == "ok"


# ---------------------------------------------------------------------------
# bf16 loss-scaled training
# ---------------------------------------------------------------------------


class TestBf16Training:
    def test_reaches_f32_class_loss(self, monkeypatch):
        f32 = build_mln().init()
        f32_losses = [f32.fit(X[i % 48:i % 48 + 8], Y[i % 48:i % 48 + 8])
                      for i in range(0, 160, 8)]
        monkeypatch.setenv(ENV_BF16, "1")
        bf16 = build_mln().init()
        bf16_losses = [bf16.fit(X[i % 48:i % 48 + 8], Y[i % 48:i % 48 + 8])
                       for i in range(0, 160, 8)]
        assert all(np.isfinite(bf16_losses))
        assert bf16_losses[-1] < bf16_losses[0]
        # bf16-class convergence: same neighborhood as the f32 run
        assert abs(bf16_losses[-1] - f32_losses[-1]) < 0.15
        # master weights stay f32; the scale state never skipped
        assert all(np.asarray(l).dtype == np.float32
                   for l in jax.tree_util.tree_leaves(bf16.params))
        snap = bf16.loss_scale
        assert snap["skipped"] == 0

    def test_scale_doubles_on_clean_streak(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        monkeypatch.setenv(ENV_SCALE, "1024:4")
        net = build_mln().init()
        for i in range(8):  # 8 clean steps at growth 4 = two doublings
            net.fit(X[:8], Y[:8])
        snap = net.loss_scale
        assert snap["scale"] == 4096.0
        assert snap["skipped"] == 0 and snap["good"] == 0

    def test_chaos_overflow_halves_and_skips(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        monkeypatch.setenv(ENV_SCALE, "1024:1000")  # no doublings in-window
        chaos = LowPrecChaos(LowPrecChaosConfig(overflow_at_step=4))
        net = build_mln().init()
        before = None
        for step in range(1, 9):
            feats = chaos.poison(step, X[:8])
            if step == 4:
                before = jax.tree_util.tree_map(np.asarray, net.params)
            loss = net.fit(feats, Y[:8])
        assert chaos.log == [(4, "overflow:inf")]
        snap = net.loss_scale
        assert snap["skipped"] == 1
        assert snap["scale"] == 512.0  # exactly one halving
        # the poisoned step was SKIPPED: master weights untouched by it
        # (steps 5..8 then moved them on)
        assert np.isfinite(loss)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(net.params))
        assert before is not None
        # loss_scale property syncs the skip count into dispatch_stats
        assert net.dispatch_stats.snapshot()["loss_scale_skips"] == 1

    def test_skip_leaves_master_weights_untouched(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        net = build_mln().init()
        net.fit(X[:8], Y[:8])  # one clean step so state is warm
        frozen = jax.tree_util.tree_map(np.asarray, net.params)
        upd_frozen = jax.tree_util.tree_map(np.asarray, net.updater_state)
        bad = LowPrecChaos(
            LowPrecChaosConfig(overflow_at_step=1, mode="nan")).poison(
                1, X[:8])
        net.fit(bad, Y[:8])
        assert params_equal(net.params, frozen)
        assert params_equal(net.updater_state, upd_frozen)
        assert net.loss_scale["skipped"] == 1

    def test_cg_bf16_trains(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        cg = build_cg().init()
        losses = [cg.fit(X[:16], Y[:16]) for _ in range(6)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
        assert cg.loss_scale["skipped"] == 0

    def test_fit_batches_scan_carries_scale(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        monkeypatch.setenv(ENV_SCALE, "1024:2")
        net = build_mln().init()
        xs = np.stack([X[:8]] * 4)
        ys = np.stack([Y[:8]] * 4)
        losses = net.fit_batches(xs, ys)
        assert np.isfinite(np.asarray(losses)).all()
        # the scale state advances INSIDE the scan: 4 clean steps at
        # growth 2 = two doublings
        assert net.loss_scale["scale"] == 4096.0


# ---------------------------------------------------------------------------
# bf16 kill/resume: bit-exact, loss scale rides the checkpoint
# ---------------------------------------------------------------------------


class TestBf16Resume:
    def test_resume_equivalence_bf16(self, monkeypatch, tmp_path):
        monkeypatch.setenv(ENV_BF16, "1")
        monkeypatch.setenv(ENV_SCALE, "1024:4")  # scale moves mid-run

        def mk_it():
            return ListDataSetIterator(X, Y, batch=8)

        baseline = ResilientTrainer(build_mln())
        baseline.fit(mk_it(), num_epochs=3)

        mgr = CheckpointManager(str(tmp_path), every_steps=4, keep_last=3)
        killed = ResilientTrainer(
            build_mln(), mgr, chaos=ChaosMonkey(ChaosConfig(kill_at_step=10)))
        with pytest.raises(InjectedKill):
            killed.fit(mk_it(), num_epochs=3)
        mgr.close()

        mgr2 = CheckpointManager(str(tmp_path), every_steps=4, keep_last=3)
        resumed = ResilientTrainer(build_mln(), mgr2)
        resumed.fit(mk_it(), num_epochs=3)
        mgr2.close()

        assert resumed.resumed_step is not None
        stitched = killed.losses[:resumed.resumed_step] + resumed.losses
        assert stitched == baseline.losses
        assert params_equal(baseline.net.params, resumed.net.params)
        assert params_equal(baseline.net.updater_state,
                            resumed.net.updater_state)
        # the loss-scale state itself resumed exactly
        assert baseline.net.loss_scale == resumed.net.loss_scale
        assert baseline.net.loss_scale["scale"] > 1024.0  # it DID move


# ---------------------------------------------------------------------------
# flagships: the scale rides the opt tree
# ---------------------------------------------------------------------------


class TestFlagshipBf16:
    def test_transformer_opt_carries_scale(self, monkeypatch, tmp_path):
        monkeypatch.setenv(ENV_BF16, "1")
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(tiny_lm_cfg())
        assert set(lowprec.OPT_SCALE_KEYS) <= set(lm.opt)
        rng = np.random.default_rng(5)
        toks = rng.integers(0, 29, (4, 16)).astype(np.int32)
        tgts = np.roll(toks, -1, axis=1)
        losses = [float(lm.fit(toks, tgts)) for _ in range(3)]
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
        assert int(lm.opt["t"]) == 3
        assert int(lm.opt["ls_skipped"]) == 0
        assert all(np.asarray(l).dtype == np.float32
                   for l in jax.tree_util.tree_leaves(lm.params))

        # save/load round-trips the scale state through the opt npz
        path = str(tmp_path / "lm.zip")
        lm.save(path)
        lm2 = TransformerLM.load(path)
        assert float(lm2.opt["loss_scale"]) == float(lm.opt["loss_scale"])
        assert int(lm2.opt["t"]) == 3
        # resumed step is bit-exact vs continuing the original
        l_a = float(lm.fit(toks, tgts))
        l_b = float(lm2.fit(toks, tgts))
        assert l_a == l_b

    def test_transformer_accum_composes(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(tiny_lm_cfg(accum_steps=2))
        rng = np.random.default_rng(6)
        toks = rng.integers(0, 29, (4, 16)).astype(np.int32)
        loss = float(lm.fit(toks, np.roll(toks, -1, axis=1)))
        assert np.isfinite(loss)
        assert int(lm.opt["ls_skipped"]) == 0

    def test_bert_opt_carries_scale(self, monkeypatch):
        monkeypatch.setenv(ENV_BF16, "1")
        from deeplearning4j_tpu.models.bert import BertConfig, BertMLM

        mlm = BertMLM(BertConfig(vocab_size=31, d_model=16, n_layers=1,
                                 n_heads=2, d_ff=32, max_len=16,
                                 learning_rate=1e-3, seed=4))
        assert set(lowprec.OPT_SCALE_KEYS) <= set(mlm.opt)
        rng = np.random.default_rng(7)
        tokens = rng.integers(4, 31, (4, 16)).astype(np.int32)
        loss = float(mlm.fit(tokens))
        assert np.isfinite(loss)
        assert int(mlm.opt["ls_skipped"]) == 0

    def test_parallel_paths_reject_loudly(self, monkeypatch):
        from deeplearning4j_tpu.models.transformer import _reject_lowprec

        monkeypatch.delenv(ENV_BF16, raising=False)
        _reject_lowprec("sequence-parallel")  # off: no-op
        monkeypatch.setenv(ENV_BF16, "1")
        with pytest.raises(ValueError, match="sequence-parallel"):
            _reject_lowprec("sequence-parallel")


# ---------------------------------------------------------------------------
# serving plane: bf16 KV arena + precision surfacing
# ---------------------------------------------------------------------------


class TestKvDtype:
    def test_block_bytes_halve(self):
        from deeplearning4j_tpu.ops import memory as memory_mod

        cfg = tiny_lm_cfg()
        f32b = memory_mod.kv_block_bytes(cfg, 16, dtype=jnp.float32)
        bf16b = memory_mod.kv_block_bytes(cfg, 16, dtype=jnp.bfloat16)
        assert f32b == 2 * bf16b

    def test_same_budget_admits_2x_blocks(self):
        from deeplearning4j_tpu.ops import memory as memory_mod

        cfg = tiny_lm_cfg()
        # budget small enough that neither side hits the max_blocks clamp
        f32n = memory_mod.kv_arena_blocks(cfg, 16, hbm_gb=0.005,
                                          dtype=jnp.float32)
        bf16n = memory_mod.kv_arena_blocks(cfg, 16, hbm_gb=0.005,
                                           dtype=jnp.bfloat16)
        assert bf16n == 2 * f32n

    def test_paged_decoder_bf16_arena(self, monkeypatch):
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.serving.paged import (
            PagedDecoder,
            attention_path,
        )

        monkeypatch.setenv(ENV_KV, "bf16")
        lm = TransformerLM(tiny_lm_cfg(max_len=32))
        # a down-cast arena under an f32 model takes the gather path
        assert attention_path(lm._run_cfg, 8) == "gather"
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            assert d.kv_dtype == jnp.dtype(jnp.bfloat16)
            assert d.kv_capacity()["kv_dtype"] == "bfloat16"
            out = d.generate(np.asarray([[1, 5, 2, 9]]), 6, temperature=0.0)
            assert len(out[0]) == 6
        finally:
            d.stop()

    def test_precision_labels(self):
        net, spec = fitted_net_and_spec()
        assert lowprec.precision_of(net) == "f32"
        assert lowprec.precision_of(
            lowprec.QuantizedNet(net, spec)) == "int8"


class TestMemoryAccounting:
    def test_preflight_train_dtype_and_activation_halving(self, monkeypatch):
        from deeplearning4j_tpu.ops import memory as memory_mod

        # big enough that the ANALYTIC activation estimate is non-zero at
        # the report's GB rounding; measure_aot=False keeps it pure math
        cfg = tiny_lm_cfg(d_model=1024, n_layers=8, n_heads=8, d_ff=4096,
                          max_len=512, vocab_size=32000)
        monkeypatch.delenv(ENV_BF16, raising=False)
        _, f32r = memory_mod.transformer_preflight(
            cfg, 32, hbm_gb=16.0, measure_aot=False)
        monkeypatch.setenv(ENV_BF16, "1")
        _, bf16r = memory_mod.transformer_preflight(
            cfg, 32, hbm_gb=16.0, measure_aot=False)
        assert f32r["train_dtype"] == "f32"
        assert bf16r["train_dtype"] == "bf16"
        # bf16 item bytes halve the activation estimate
        assert bf16r["activations_gb_est"] == pytest.approx(
            f32r["activations_gb_est"] / 2, rel=0.01)
