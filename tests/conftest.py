"""Test env: force a virtual 8-device CPU platform BEFORE jax initializes.

Mirrors the reference's distributed-without-a-cluster test strategy
(SURVEY.md section 4: Spark local[N] in BaseSparkTest.java:90) — multi-chip
logic is tested on a virtual CPU mesh via
XLA_FLAGS=--xla_force_host_platform_device_count=8.

float64 is enabled for the gradient-check suite (the reference enforces
double precision there, GradientCheckUtil.java).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell env may point at a TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon already in the env, so the env vars above are too late
# for jax's import-time config read — set the config directly (backends have
# not initialized yet when conftest runs).
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)
