"""Test env: force a virtual 8-device CPU platform BEFORE jax initializes.

Mirrors the reference's distributed-without-a-cluster test strategy
(SURVEY.md section 4: Spark local[N] in BaseSparkTest.java:90) — multi-chip
logic is tested on a virtual CPU mesh via
XLA_FLAGS=--xla_force_host_platform_device_count=8.

float64 is enabled for the gradient-check suite (the reference enforces
double precision there, GradientCheckUtil.java).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell env may point at a TPU
# REPLACE any inherited device-count flag (an =2 left over from a multihost
# worker env would otherwise silently win on the 0.4.x image, where the
# jax_num_cpu_devices fallback below is swallowed) — same discipline as
# tests/multihost_worker.py and __graft_entry__._set_cpu_device_count
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon already in the env, so the env vars above are too late
# for jax's import-time config read — set the config directly (backends have
# not initialized yet when conftest runs).
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # this environment's jax predates the jax_num_cpu_devices option; the
    # XLA_FLAGS fallback set above (before first backend use — the flags
    # are read at CPU-client creation, not jax import) provides the
    # 8-device virtual mesh instead. Without the try/except the whole
    # suite dies at collection.
    pass
jax.config.update("jax_enable_x64", True)

# ---------------------------------------------------------------------------
# Test tiers (VERDICT r4 #7): `-m quick` is the ~2-minute gate — the
# highest-value correctness tests (closed-form updater math, weight-init
# stats, conf round-trip, MLP/CNN gradient checks, MultiLayerNetwork core
# equivalences, the bench/watcher capture machinery) — so changes can be
# validated without the ~38-minute full suite colliding with a live
# tunnel window on this 1-core host. The full suite remains the bar;
# quick is triage.
# ---------------------------------------------------------------------------

_QUICK_FILES = {
    "test_updaters.py",
    "test_weight_init.py",
    "test_conf_serde.py",
    "test_kernel_gate.py",
    "test_bench_artifact.py",
    "test_bench_preflight.py",
    "test_bench_watch_sh.py",
    "test_gradient_check.py",
    "test_multilayer.py",
    "test_dispatch.py",
    # remat==no-remat value contracts + the AOT memory ladder (ISSUE 4)
    "test_remat.py",
    # the whole resilience suite (incl. the subprocess SIGTERM preemption
    # leg, ~6s) fits the quick budget — crash-recovery is exactly the kind
    # of contract a mid-round change can silently break
    "test_resilience.py",
    # ETL plane (ISSUE 5): transform/normalizer value contracts plus the
    # pipeline==serial byte-equivalence and kill/resume-through-pipeline
    # contracts — both files run in seconds on tiny nets
    "test_etl.py",
    "test_input_pipeline.py",
    # elastic fleet (ISSUE 6): the headline worker-loss/rejoin == replay
    # bit-exactness + == serial contracts (~15s on tiny nets); the
    # OS-process-worker leg is excluded below (full tier covers it)
    "test_fleet.py",
    # observability plane (ISSUE 7): obs-off == obs-on bit-exactness, the
    # ledger-registration convention, Prometheus golden exposition, the
    # five-ledgers-in-one-scrape contract — seconds on tiny nets
    "test_obs.py",
    # serving resilience plane (ISSUE 8): chaos-driven breaker/watchdog/
    # drain/isolation contracts — deterministic injected faults on tiny
    # nets, the serving third of the crash-recovery convention
    "test_serving_resilience.py",
    # paged-KV serving plane (ISSUE 11): block-pool request independence
    # (solo==coscheduled across prefix sharing/preemption), crash
    # eviction, SLO shed, streaming, arena sizing — ~15s on tiny LMs
    "test_serving_paged.py",
    # serving fleet (ISSUE 12): router+replicas byte-identity vs a solo
    # engine, chaos-killed replica => zero failed admitted requests,
    # rollout auto-rollback never moving a serving default, fleet-wide
    # SLO shed, breaker eject/half-open re-admit — deterministic chaos
    # on tiny nets, in-process replicas (~20s); OS-process replicas are
    # full tier (test_serving_fleet_process.py)
    "test_serving_fleet.py",
    # graftlint (ISSUE 10): per-rule fixture contracts + the repo-wide
    # clean sweep + the knob-table↔CLAUDE.md consistency gate — pure-AST,
    # jax-free, seconds for the fixtures and ~15s for the sweep
    "test_analysis.py",
    # kernel rent program (ISSUE 13): interpret-mode CPU equivalence for
    # the paged-decode attention + fused SGNS kernels (value, tick/epoch,
    # forced-transcript, and gate contracts) — tiny shapes, ~30s
    "test_pallas_paged.py",
    "test_pallas_sgns.py",
    # online learning loop (ISSUE 14): kill/resume through a live
    # StreamSource bit-exactness, zero-failed-request promotion swap,
    # deterministic drift veto, mirror byte-invisibility — tiny nets,
    # ~15s
    "test_online.py",
    # low-precision plane (ISSUE 15): int8 value/gate fail-safe contracts,
    # bf16 loss-scaling (chaos-forced halving, kill/resume bit-exactness,
    # flagship opt-tree scale state), bf16 KV arena sizing — tiny nets,
    # ~40s
    "test_lowprec.py",
    # decode amortization (ISSUE 16): k-tick == k x 1-tick byte-identity
    # across the paged contract matrix, speculative greedy == target-only
    # greedy (chaos all-reject included), acceptance ledger arithmetic,
    # knob registration — tiny LMs, ~30s
    "test_speculate.py",
    # embedding & retrieval plane (ISSUE 17): /embed batcher==direct
    # byte-equivalence (pad rows inert), exact-index vs numpy oracle,
    # MEASURED IVF recall, zero-failed-/search across a generation swap,
    # drift veto, knob/ledger registration — tiny nets, ~20s
    "test_retrieval.py",
    # mesh-sharded inference plane (ISSUE 18): sharded tick == solo tick
    # byte-identity across the paged contract matrix (prefix sharing /
    # preemption / crash eviction / streaming), loud incompatibility
    # gates, per-device arena closed forms, role-aware router dispatch +
    # the prefill->decode handoff, knob/ledger registration — tiny LMs
    # on the virtual CPU mesh, ~40s
    "test_serving_mesh.py",
    # autoscaling plane (ISSUE 20): deterministic scale-decision replay,
    # chaos load wave -> scale-up -> scale-down racing live /predict +
    # /generate with zero failed admitted requests, tenant-bucket
    # fairness, FFD placement + affinity 503 loudness, goodbye ordering,
    # knob/ledger/leg registration — tiny nets, ~40s
    "test_autoscale.py",
}
# float64 recurrent gradchecks cost ~2 min alone — full-suite only; the
# attention/MoE/BERT checks (VERDICT r5 ask #6) cost ~80s together and
# join them outside the quick budget
_QUICK_EXCLUDE = {"test_rnn_masked_gradients", "test_lstm_gradients",
                  "test_gru_gradients", "test_mha_gradients",
                  "test_moe_ffn_gradients", "test_bert_mlm_loss_gradients",
                  # 3 subprocess coordinators + workers (~30s): full tier
                  "test_corrupt_checkpoint_fleet_restore_multiprocess"}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: fast high-value gate (see CLAUDE.md test tiers)")
    config.addinivalue_line(
        "markers", "examples: subprocess smoke runs of every stock "
        "examples/*.py entrypoint (tiny shapes, forced CPU)")


def pytest_collection_modifyitems(config, items):
    seen_files = set()
    seen_names = set()
    for item in items:
        base = os.path.basename(str(item.fspath))
        if base in _QUICK_FILES:
            seen_files.add(base)
            name = item.name.split("[")[0]
            seen_names.add(name)
            if name not in _QUICK_EXCLUDE:
                item.add_marker(pytest.mark.quick)
    # Stale-exclusion guard (ADVICE r5): a renamed/removed slow test must
    # fail collection LOUDLY, not silently re-enter the 2-minute quick
    # gate. Only enforced when every quick file was collected (a partial
    # run — one file, a -k filter — legitimately misses names).
    if seen_files >= _QUICK_FILES:
        stale = _QUICK_EXCLUDE - seen_names
        if stale:
            raise pytest.UsageError(
                f"_QUICK_EXCLUDE entries never seen in collection: "
                f"{sorted(stale)} — the excluded tests were renamed or "
                "removed; update tests/conftest.py so the quick tier "
                "stays honest"
            )
