"""Mesh-sharded inference plane (ISSUE 18): tensor-parallel decode over
a sharded KV arena + prefill/decode disaggregation.

The plane's acceptance bar is BYTE-identity, not tolerance: the sharded
tick never runs a psum (serving/mesh.py module docstring — column-
parallel QKV by exact weight-column slicing, per-head local attention,
all_gather CONCATENATION, replicated Wo/MLP/logits), so
MeshPagedDecoder must equal the single-device PagedDecoder bit-for-bit
across the WHOLE paged contract matrix: prefix sharing, preemption-by-
recompute, crash eviction, streaming order, k-ticks, sampled lanes.

Incompatibility is LOUD by contract: a knob combination the sharded
plane cannot honor byte-exactly (bf16 KV arena, speculative decode,
indivisible heads, no paged pool) raises at decoder build and surfaces
per-record in /models — never a silent fallback to the dense path.

Disaggregation: a prefill-role replica runs long-prompt prefill as its
own dispatch and hands content-addressed KV blocks to a decode replica
(/prefill -> /prime through the role-aware FleetRouter); the handoff is
best-effort BY CONSTRUCTION, so tokens are byte-identical whether or
not it lands.

Reference anchor: the reference serves one record per route callback
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java) and has no model
parallelism at all (SURVEY.md section 2.7); provenance for the sharded
decode is the repo's own tensor_parallel plane + the vLLM/Orca pair
cited in serving/paged.py.
"""

import json
import os
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ops import env
from deeplearning4j_tpu.resilience import (
    InjectedServingFault,
    ServingChaos,
    ServingChaosConfig,
)
from deeplearning4j_tpu.serving import ServingEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MESH_D = 4  # of the 8 virtual devices conftest forces


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=MESH_D,
              d_ff=32, max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


def _post(url, path, payload, timeout=240):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, path, timeout=60):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# byte-identity across the paged contract matrix
# ---------------------------------------------------------------------------


class TestMeshByteIdentity:
    def test_coscheduled_equals_solo_greedy_and_sampled(self):
        """Sharded tick == solo tick BYTE-identical with greedy and
        temperature-sampled lanes co-resident (the threefry keys are
        replicated, so sampling is bitwise the same program)."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        reqs = [([1, 5, 2, 9], dict(temperature=0.0)),
                ([4, 4, 4], dict(temperature=0.8, seed=7)),
                ([9, 8, 7, 6, 5], dict(temperature=0.0))]

        def run(d):
            try:
                futs = [d.submit(p, 6, **kw) for p, kw in reqs]
                return [f.result(timeout=240).tolist() for f in futs]
            finally:
                d.stop()

        solo = run(PagedDecoder(lm, block_tokens=4, n_blocks=16))
        sharded = run(MeshPagedDecoder(lm, devices=MESH_D,
                                       block_tokens=4, n_blocks=16))
        assert sharded == solo

    def test_prefix_sharing_equals_solo(self):
        """Prefix-cache hits on the head-sharded arena: shared prompt
        blocks are read-only to both lanes (write tables at trash) and
        the tokens equal the dense pool's."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        shared = [2, 4, 6, 8, 10, 12, 14, 16, 3, 5]  # > two 4-tok blocks
        d0 = PagedDecoder(lm, block_tokens=4, n_blocks=16)
        try:
            base_a = d0.generate(np.asarray([shared + [7]]), 5,
                                 temperature=0.0)[0]
            base_b = d0.generate(np.asarray([shared + [9]]), 5,
                                 temperature=0.0)[0]
        finally:
            d0.stop()
        d = MeshPagedDecoder(lm, devices=MESH_D, block_tokens=4,
                             n_blocks=16)
        try:
            f1 = d.submit(shared + [7], 5, temperature=0.0)
            f2 = d.submit(shared + [9], 5, temperature=0.0)
            np.testing.assert_array_equal(base_a, f1.result(timeout=240))
            np.testing.assert_array_equal(base_b, f2.result(timeout=240))
            assert d.stats.prefix_hits > 0
        finally:
            d.stop()

    def test_preemption_recovery_is_exact(self):
        """Block starvation preempts the youngest admission on the
        sharded arena exactly as on the dense one: recompute-from-window
        lands tokens byte-identical to an uninterrupted dense run."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        prompts = ([2, 4, 6], [1, 1, 1, 1], [9, 8, 7])
        d0 = PagedDecoder(lm, block_tokens=8, n_blocks=16)
        try:
            bases = [d0.generate(np.asarray([p]), 20,
                                 temperature=0.0)[0] for p in prompts]
        finally:
            d0.stop()
        # 7 blocks * 8 tokens cannot hold three 23/24-token sequences
        d = MeshPagedDecoder(lm, devices=MESH_D, block_tokens=8,
                             n_blocks=7)
        try:
            futs = [d.submit(list(p), 20, temperature=0.0)
                    for p in prompts]
            outs = [f.result(timeout=240) for f in futs]
            assert d.stats.preemptions >= 1
        finally:
            d.stop()
        for base, out in zip(bases, outs):
            np.testing.assert_array_equal(base, out)

    def test_crash_eviction_spares_coresidents(self):
        """A chaos-crashed admission fails ONLY its own future; the
        co-resident's tokens stay byte-equal to solo and the freed
        blocks return (PR 8 semantics on the sharded arena)."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        lm = tiny_lm()
        chaos = ServingChaos(ServingChaosConfig(admit_raise_at=3))
        d = MeshPagedDecoder(lm, devices=MESH_D, block_tokens=8,
                             n_blocks=16, chaos=chaos)
        try:
            prompt = [1, 5, 2, 9]
            solo = d.generate(np.asarray([prompt]), 8, temperature=0.0)[0]
            long_fut = d.submit(prompt, 8, temperature=0.0)
            time.sleep(0.05)
            crash_fut = d.submit([3, 3, 4], 6, temperature=0.0)
            with pytest.raises(InjectedServingFault):
                crash_fut.result(timeout=120)
            np.testing.assert_array_equal(solo,
                                          long_fut.result(timeout=240))
            assert d.stats.slot_crashes == 1
            cap = d.kv_capacity()
            assert cap["blocks_in_use"] == cap["prefix_blocks_cached"]
        finally:
            d.stop()

    def test_streaming_order_matches_result(self):
        """on_token fires per tick in emission order on the sharded
        pool — the streamed sequence IS the final result."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        lm = tiny_lm()
        d = MeshPagedDecoder(lm, devices=MESH_D, block_tokens=4,
                             n_blocks=16)
        try:
            streamed = []
            fut = d.submit([1, 5, 2, 9], 6, temperature=0.0,
                           on_token=streamed.append)
            out = fut.result(timeout=240)
            assert streamed == list(out)
        finally:
            d.stop()

    def test_k_tick_equals_one_tick(self):
        """The k-scanned sharded tick == the 1-tick sharded program ==
        the dense pool, byte-identical (ISSUE 16's amortization contract
        carried onto the mesh)."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        outs = []
        for mk in (dict(), dict(devices=MESH_D),
                   dict(devices=MESH_D, tick_k=4)):
            cls = MeshPagedDecoder if "devices" in mk else PagedDecoder
            d = cls(lm, block_tokens=4, n_blocks=16, **mk)
            try:
                outs.append(d.generate(np.asarray([[1, 5, 2, 9]]), 8,
                                       temperature=0.0)[0].tolist())
            finally:
                d.stop()
        assert outs[0] == outs[1] == outs[2]


# ---------------------------------------------------------------------------
# loud incompatibility gates
# ---------------------------------------------------------------------------


class TestLoudGates:
    def test_indivisible_heads_rejects(self):
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        with pytest.raises(ValueError, match="divisible"):
            MeshPagedDecoder(tiny_lm(n_heads=3), devices=MESH_D,
                             block_tokens=4, n_blocks=16)

    def test_single_device_rejects(self):
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        with pytest.raises(ValueError, match="devices"):
            MeshPagedDecoder(tiny_lm(), devices=1, block_tokens=4,
                             n_blocks=16)

    def test_bf16_kv_rejects(self, monkeypatch):
        """DL4J_TPU_SERVE_KV_DTYPE=bf16 x mesh raises at build — the
        arena cast would make the sharded tick's bytes diverge from the
        dense f32 pool, so it must never be silent."""
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        monkeypatch.setenv("DL4J_TPU_SERVE_KV_DTYPE", "bf16")
        with pytest.raises(ValueError, match="KV_DTYPE"):
            MeshPagedDecoder(tiny_lm(), devices=MESH_D, block_tokens=4,
                             n_blocks=16)

    def test_spec_mode_rejects(self, monkeypatch):
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder

        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "int8")
        with pytest.raises(ValueError, match="SPEC"):
            MeshPagedDecoder(tiny_lm(), devices=MESH_D, block_tokens=4,
                             n_blocks=16)

    def test_engine_mesh_requires_paged_pool(self):
        """Mesh over the fixed-slot pool is a contradiction (no sharded
        arena): the engine raises LOUDLY instead of quietly serving the
        dense fixed-slot path."""
        lm = tiny_lm()
        eng = ServingEngine(model=lm, kv_block=0, mesh_devices=MESH_D)
        try:
            with pytest.raises(ValueError, match="KV_BLOCK"):
                eng._decoder_for(eng.registry.default())
        finally:
            eng.stop()

    def test_engine_gate_error_is_loud_not_fallback(self):
        """A mesh-ineligible model (indivisible heads) must NOT land in
        _no_decoder and serve dense: /generate answers 400 with the gate
        error and /models carries it per record."""
        lm = tiny_lm(n_heads=3)
        eng = ServingEngine(model=lm, kv_block=4, kv_blocks=16,
                            mesh_devices=MESH_D).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(eng.url, "/generate", {"tokens": [1, 2, 3],
                                             "n_new": 2,
                                             "temperature": 0.0})
            assert exc.value.code == 400
            assert "divisible" in json.loads(exc.value.read())["error"]
            kv = _get(eng.url, "/models")["kv"]["default@v1"]
            assert "divisible" in kv["error"]
            # the record was NOT blacklisted into the silent-dense set
            assert not eng._no_decoder
        finally:
            eng.stop()

    def test_engine_role_validated(self):
        with pytest.raises(ValueError, match="SERVE_ROLE"):
            ServingEngine(model=tiny_lm(), role="sideways")


# ---------------------------------------------------------------------------
# per-device arena accounting (ops/memory.py closed forms)
# ---------------------------------------------------------------------------


class TestArenaSizing:
    def test_kv_block_bytes_devices_closed_form(self):
        """devices=d divides the HEAD axis (ceil) in the per-device
        block footprint: 2 (k+v) * L * bt * ceil(H/d) * hd * itemsize."""
        from deeplearning4j_tpu.ops import memory as opsmem

        cfg = tiny_lm()._run_cfg
        one = opsmem.kv_block_bytes(cfg, 8)
        for d in (1, 2, 4):
            per = opsmem.kv_block_bytes(cfg, 8, devices=d)
            hl = -(-cfg.n_heads // d)
            want = 2 * cfg.n_layers * 8 * hl * (
                cfg.d_model // cfg.n_heads) * 4
            assert per == want
            assert per == one // d  # H=4 divides evenly here

    def test_kv_arena_blocks_scales_with_devices(self):
        """At a fixed per-device HBM budget, the global arena admits ~d
        times the blocks: capacity scales with the mesh (the tentpole's
        capacity claim, closed-form — no device needed)."""
        from deeplearning4j_tpu.ops import memory as opsmem

        cfg = tiny_lm()._run_cfg
        n1 = opsmem.kv_arena_blocks(cfg, 8, hbm_gb=0.001)
        n4 = opsmem.kv_arena_blocks(cfg, 8, hbm_gb=0.001, devices=4)
        assert n4 == 4 * n1

    def test_kv_capacity_stamps_mesh_devices(self):
        from deeplearning4j_tpu.serving.mesh import MeshPagedDecoder
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=4, n_blocks=16)
        try:
            assert d.kv_capacity()["mesh_devices"] == 1
        finally:
            d.stop()
        d = MeshPagedDecoder(lm, devices=MESH_D, block_tokens=4,
                             n_blocks=16)
        try:
            assert d.kv_capacity()["mesh_devices"] == MESH_D
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# role-aware routing + the prefill->decode handoff
# ---------------------------------------------------------------------------


class TestRolesAndHandoff:
    def test_addr_role_roundtrip_and_backcompat(self, tmp_path):
        from deeplearning4j_tpu.serving.router import (
            publish_replica_addr,
            read_replica_entry,
        )

        publish_replica_addr(str(tmp_path), "r0", "http://x:1",
                             role="prefill")
        entry = read_replica_entry(str(tmp_path), "r0")
        assert entry == {"url": "http://x:1", "role": "prefill"}
        # an addr file written before the role field existed
        with open(os.path.join(str(tmp_path), "replica-r1.addr"),
                  "w") as f:
            json.dump({"url": "http://y:2", "pid": 1}, f)
        assert read_replica_entry(str(tmp_path), "r1") == {
            "url": "http://y:2", "role": ""}

    def test_disaggregated_generate_byte_equal_and_routed(self):
        """/generate through a prefill+decode fleet: every request is
        answered byte-equal to a solo engine, decode traffic never lands
        on the prefill replica, and the handoff adopts blocks that the
        decode replica's admission then HITS in its prefix cache."""
        from deeplearning4j_tpu.serving.router import FleetRouter

        lm = tiny_lm()
        prompt = [1, 5, 2, 9, 3, 7, 4, 8, 6, 2]
        solo = ServingEngine(model=lm, kv_block=4, kv_blocks=24).start()
        try:
            want = _post(solo.url, "/generate",
                         {"tokens": prompt, "n_new": 6,
                          "temperature": 0.0})["tokens"][0]
        finally:
            solo.stop()
        pre = ServingEngine(model=lm, kv_block=4, kv_blocks=24,
                            role="prefill").start()
        dec = ServingEngine(model=lm, kv_block=4, kv_blocks=24,
                            role="decode").start()
        router = FleetRouter(replicas={
            "p0": {"url": pre.url, "role": "prefill"},
            "d0": {"url": dec.url, "role": "decode"},
        }).start()
        try:
            for _ in range(2):
                got = _post(router.url, "/generate",
                            {"tokens": prompt, "n_new": 6,
                             "temperature": 0.0})["tokens"][0]
                assert got == want
            snap = router.stats.snapshot()
            assert snap["prefill_handoffs"] >= 1
            ps, ds = pre.stats.snapshot(), dec.stats.snapshot()
            assert ps["prefix_exports"] >= 1
            assert ps["generated_tokens"] == 0  # no decode leak
            assert ds["prefix_imports"] >= 1
            assert ds["prefix_hits"] >= 1
            assert ds["errors"] == 0 and ds["completed"] == 2
            desc = router.describe_replicas()
            assert desc["p0"]["role"] == "prefill"
        finally:
            router.stop()
            pre.stop()
            dec.stop()

    def test_handoff_failure_falls_back_byte_identical(self):
        """A dead prefill replica degrades to the direct decode path —
        same tokens, fallback counted, zero failed requests (the
        best-effort-by-construction contract)."""
        from deeplearning4j_tpu.serving.router import FleetRouter

        lm = tiny_lm()
        prompt = [1, 5, 2, 9, 3, 7, 4, 8, 6, 2]
        dec = ServingEngine(model=lm, kv_block=4, kv_blocks=24).start()
        want = None
        router = FleetRouter(replicas={
            # unroutable prefill replica (nothing listens there)
            "p0": {"url": "http://127.0.0.1:9", "role": "prefill"},
            "d0": {"url": dec.url, "role": "decode"},
        }).start()
        try:
            got = _post(router.url, "/generate",
                        {"tokens": prompt, "n_new": 6,
                         "temperature": 0.0})["tokens"][0]
            want = dec.generate(np.asarray([prompt]), 6,
                                temperature=0.0)[0].tolist()
            assert got == want
            snap = router.stats.snapshot()
            assert snap["prefill_fallbacks"] == 1
            assert snap["prefill_handoffs"] == 0
        finally:
            router.stop()
            dec.stop()

    def test_short_prompt_skips_handoff(self):
        """A prompt below one full block has nothing to hand off: no
        fallback counted, no /prime, tokens still byte-equal."""
        from deeplearning4j_tpu.serving.router import FleetRouter

        lm = tiny_lm()
        pre = ServingEngine(model=lm, kv_block=8, kv_blocks=24,
                            role="prefill").start()
        dec = ServingEngine(model=lm, kv_block=8, kv_blocks=24,
                            role="decode").start()
        router = FleetRouter(replicas={
            "p0": {"url": pre.url, "role": "prefill"},
            "d0": {"url": dec.url, "role": "decode"},
        }).start()
        try:
            _post(router.url, "/generate", {"tokens": [1, 5, 2],
                                            "n_new": 4,
                                            "temperature": 0.0})
            snap = router.stats.snapshot()
            assert snap["prefill_handoffs"] == 0
            assert snap["prefill_fallbacks"] == 0
            assert dec.stats.snapshot()["prefix_imports"] == 0
        finally:
            router.stop()
            pre.stop()
            dec.stop()


# ---------------------------------------------------------------------------
# knob + ledger + bench-leg registration
# ---------------------------------------------------------------------------


class TestRegistration:
    def test_knobs_registered(self):
        for name in ("DL4J_TPU_SERVE_MESH", "DL4J_TPU_SERVE_ROLE"):
            assert env.is_registered(name), name

    def test_prefix_handoff_counters_in_ledgers(self):
        """The new telemetry fields ride the existing registered
        ledgers (serving_stats / router_stats) — one scrape surface."""
        from deeplearning4j_tpu.serving.router import RouterStats
        from deeplearning4j_tpu.serving.telemetry import ServingStats

        s = ServingStats()
        s.record_prefix_export()
        s.record_prefix_import(3)
        snap = s.snapshot()
        assert snap["prefix_exports"] == 1
        assert snap["prefix_imports"] == 1
        assert snap["prefix_import_blocks"] == 3
        r = RouterStats()
        r.record_prefill_handoff()
        r.record_prefill_fallback()
        snap = r.snapshot()
        assert snap["prefill_handoffs"] == 1
        assert snap["prefill_fallbacks"] == 1

    def test_serving_mesh_leg_registered(self):
        """bench.py defines the serving_mesh leg, bench_state expects
        it, and it is CPU-only (runs with the tunnel down)."""
        from scripts.bench_state import EXPECTED

        assert "serving_mesh" in EXPECTED
        src = open(os.path.join(REPO, "bench.py")).read()
        legs = set(re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M))
        assert "serving_mesh" in legs
        cpu_only = re.search(r"_CPU_ONLY_LEGS\s*=\s*\{([^}]*)\}", src)
        assert "serving_mesh" in cpu_only.group(1)
