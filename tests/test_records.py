"""Record reader + assembly tests — mirrors the reference's record-reader
iterator tests (RecordReaderDataSetIteratorTest, sequence variants with
variable-length masking per TestVariableLengthTS)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.records import (
    ALIGN_END,
    CSVRecordReader,
    CSVSequenceRecordReader,
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    LineRecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)


class TestReaders:
    def test_csv_reader_skip_lines(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("header,row\n1,2\n3,4\n")
        recs = list(CSVRecordReader(str(p), skip_lines=1))
        assert recs == [["1", "2"], ["3", "4"]]

    def test_line_reader(self, tmp_path):
        p = tmp_path / "lines.txt"
        p.write_text("alpha\nbeta\n")
        assert list(LineRecordReader(str(p))) == [["alpha"], ["beta"]]

    def test_csv_sequence_reader_sorted_files(self, tmp_path):
        (tmp_path / "b.csv").write_text("3,4\n")
        (tmp_path / "a.csv").write_text("1,2\n5,6\n")
        seqs = list(CSVSequenceRecordReader(str(tmp_path)))
        assert seqs[0] == [["1", "2"], ["5", "6"]]  # a.csv first
        assert seqs[1] == [["3", "4"]]


class TestRecordReaderDataSetIterator:
    def test_classification_one_hot(self):
        reader = CollectionRecordReader(
            [[0.1, 0.2, 1], [0.3, 0.4, 0], [0.5, 0.6, 2]]
        )
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         num_possible_labels=3)
        batches = list(it)
        assert len(batches) == 2
        assert batches[0].features.shape == (2, 2)
        np.testing.assert_array_equal(batches[0].labels,
                                      [[0, 1, 0], [1, 0, 0]])

    def test_regression_label(self):
        reader = CollectionRecordReader([[1.0, 2.0, 0.5], [3.0, 4.0, 0.7]])
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         regression=True)
        ds = next(iter(it))
        np.testing.assert_allclose(ds.labels.reshape(-1), [0.5, 0.7])

    def test_multi_column_regression(self):
        reader = CollectionRecordReader([[1, 2, 9, 8], [3, 4, 7, 6]])
        it = RecordReaderDataSetIterator(reader, batch_size=2, label_index=2,
                                         label_index_to=3, regression=True)
        ds = next(iter(it))
        assert ds.features.shape == (2, 2)
        np.testing.assert_allclose(ds.labels, [[9, 8], [7, 6]])

    def test_reiterable(self):
        reader = CollectionRecordReader([[1.0, 0], [2.0, 1]])
        it = RecordReaderDataSetIterator(reader, 2, label_index=1,
                                         num_possible_labels=2)
        assert len(list(it)) == 1
        assert len(list(it)) == 1  # reader reset


class TestSequenceIterator:
    def test_variable_length_masking(self):
        seqs = [
            [[1, 0], [2, 0], [3, 1]],        # T=3
            [[4, 1]],                        # T=1
        ]
        reader = CollectionSequenceRecordReader(seqs)
        it = SequenceRecordReaderDataSetIterator(
            reader, batch_size=2, label_index=1, num_possible_labels=2,
        )
        ds = next(iter(it))
        assert ds.features.shape == (2, 3, 1)
        assert ds.labels.shape == (2, 3, 2)
        np.testing.assert_array_equal(ds.features_mask, [[1, 1, 1], [1, 0, 0]])
        np.testing.assert_allclose(ds.features[1, 0], [4.0])

    def test_align_end(self):
        seqs = [[[1, 0], [2, 1]], [[9, 1]]]
        reader = CollectionSequenceRecordReader(seqs)
        it = SequenceRecordReaderDataSetIterator(
            reader, batch_size=2, label_index=1, num_possible_labels=2,
            align_mode=ALIGN_END,
        )
        ds = next(iter(it))
        np.testing.assert_array_equal(ds.features_mask, [[1, 1], [0, 1]])
        np.testing.assert_allclose(ds.features[1, 1], [9.0])

    def test_separate_label_reader(self):
        f_reader = CollectionSequenceRecordReader([[[1, 2], [3, 4]]])
        l_reader = CollectionSequenceRecordReader([[[0], [1]]])
        it = SequenceRecordReaderDataSetIterator(
            f_reader, batch_size=1, labels_reader=l_reader,
            num_possible_labels=2,
        )
        ds = next(iter(it))
        assert ds.features.shape == (1, 2, 2)
        np.testing.assert_array_equal(ds.labels[0], [[1, 0], [0, 1]])

    def test_feeds_rnn_training(self):
        """End-to-end: masked variable-length batch into an LSTM fit."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        rng = np.random.default_rng(0)
        seqs = []
        for _ in range(8):
            t = int(rng.integers(2, 6))
            seqs.append([[float(rng.normal()), int(rng.integers(0, 2))]
                         for _ in range(t)])
        reader = CollectionSequenceRecordReader(seqs)
        it = SequenceRecordReaderDataSetIterator(
            reader, batch_size=8, label_index=1, num_possible_labels=2,
        )
        conf = (
            NeuralNetConfiguration.builder().seed(1).learning_rate(0.05).list()
            .layer(0, GravesLSTM(n_in=1, n_out=8, activation="tanh"))
            .layer(1, RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                     loss_function="mcxent"))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        ds = next(iter(it))
        loss = net.fit(ds.features, ds.labels, ds.features_mask, ds.labels_mask)
        assert np.isfinite(float(loss))


class TestMultiDataSetIterator:
    def test_named_readers_and_routing(self):
        r1 = CollectionRecordReader([[1, 2, 0], [3, 4, 1], [5, 6, 2]])
        r2 = CollectionRecordReader([[10], [20], [30]])
        it = (
            RecordReaderMultiDataSetIterator(batch_size=2)
            .add_reader("main", r1)
            .add_reader("aux", r2)
            .add_input("main", 0, 1)
            .add_input("aux", 0)
            .add_output_one_hot("main", 2, 3)
        )
        batches = list(it)
        assert len(batches) == 2
        mds = batches[0]
        assert mds.features_list[0].shape == (2, 2)
        assert mds.features_list[1].shape == (2, 1)
        np.testing.assert_array_equal(mds.labels_list[0],
                                      [[1, 0, 0], [0, 1, 0]])
        assert batches[1].features_list[0].shape == (1, 2)


class TestDataSetUtilitySurface:
    """The reference DataSet's in-place utility methods, in usage order
    (normalizeZeroMeanZeroUnitVariance 31 uses, sample 19, shuffle 15,
    splitTestAndTrain 9, normalize 7, scale 3 across the reference)."""

    def _ds(self, n=10, f=4, seed=0):
        from deeplearning4j_tpu.datasets.iterator import DataSet

        rng = np.random.default_rng(seed)
        return DataSet(rng.standard_normal((n, f)) * 3 + 5,
                       np.eye(2)[rng.integers(0, 2, n)])

    def test_standardize_columns(self):
        ds = self._ds()
        ds.normalize_zero_mean_zero_unit_variance()
        np.testing.assert_allclose(ds.features.mean(0), 0, atol=1e-6)
        np.testing.assert_allclose(ds.features.std(0), 1, atol=1e-5)

    def test_standardize_constant_column_safe(self):
        ds = self._ds()
        ds.features[:, 1] = 7.0
        ds.normalize_zero_mean_zero_unit_variance()
        assert np.isfinite(ds.features).all()
        np.testing.assert_allclose(ds.features[:, 1], 0, atol=1e-6)

    def test_normalize_to_unit_range(self):
        ds = self._ds()
        ds.normalize()
        assert ds.features.min() == 0.0 and ds.features.max() == 1.0

    def test_scale_by_max_abs(self):
        ds = self._ds()
        m = np.abs(ds.features).max()
        ref = np.asarray(ds.features) / m
        ds.scale()
        np.testing.assert_allclose(ds.features, ref, rtol=1e-6)

    def test_shuffle_keeps_pairs(self):
        ds = self._ds()
        pairs = {tuple(np.round(fv, 6)): tuple(lv)
                 for fv, lv in zip(ds.features, ds.labels)}
        ds.shuffle(seed=3)
        for fv, lv in zip(ds.features, ds.labels):
            assert pairs[tuple(np.round(fv, 6))] == tuple(lv)

    def test_sample_without_replacement_unique(self):
        ds = self._ds(n=8)
        s = ds.sample(8, seed=1)
        assert s.num_examples() == 8
        assert len({tuple(np.round(r, 6)) for r in s.features}) == 8
        import pytest

        with pytest.raises(ValueError):
            ds.sample(9)

    def test_sample_with_replacement(self):
        ds = self._ds(n=4)
        s = ds.sample(16, seed=2, with_replacement=True)
        assert s.num_examples() == 16

    def test_split_test_and_train(self):
        ds = self._ds(n=10)
        sp = ds.split_test_and_train(7)
        assert sp.train.num_examples() == 7
        assert sp.test.num_examples() == 3
        np.testing.assert_array_equal(sp.train.features,
                                      np.asarray(ds.features)[:7])
        import pytest

        with pytest.raises(ValueError):
            ds.split_test_and_train(10)

    def test_float_dtype_preserved_through_utilities(self):
        """f64 pipelines (the forced-x64 equivalence regime) must not be
        silently downcast by any in-place utility; int features
        standardize to float32."""
        from deeplearning4j_tpu.datasets.iterator import DataSet

        f64 = self._ds()
        assert np.asarray(f64.features).dtype == np.float64
        f64.normalize_zero_mean_zero_unit_variance().normalize().scale()
        assert f64.features.dtype == np.float64
        ints = DataSet(np.arange(12).reshape(4, 3), np.eye(2)[[0, 1, 0, 1]])
        ints.normalize()
        assert ints.features.dtype == np.float32
