"""The tunnel watcher's state machine, executed for real.

scripts/bench_watch.sh is the round's critical capture machine, but its
quick->full->w2v path has never run live (the tunnel never stayed up).
This harness runs the ACTUAL script in a stub repo: a permissive fake
`jax` makes the probe succeed instantly, a stub `bench.py` plays
scripted scenarios into the real artifact files, and the REAL
scripts/bench_state.py checker arbitrates completeness — so the shell
logic (gap-filling loop, caps, artifact-based w2v retry, honest exit
lines) is what's under test, not stand-ins for it."""
import json
import os
import shutil
import stat
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_JAX = '''
"""Permissive jax stub: the watcher's PROBE only needs devices()[0]
.platform != 'cpu' and a summable ones((2,)); sitecustomize (if any)
touching other attributes gets inert callables."""
class _Dev:
    platform = "stub-tpu"
    def __repr__(self):
        return "StubTPU"


def devices():
    return [_Dev()]


def __getattr__(name):
    class _Inert:
        def __call__(self, *a, **k):
            return self
        def __getattr__(self, n):
            return self
    return _Inert()
'''

# The stub bench plays a scenario from BENCH_STUB file: each line is one
# planned invocation outcome ("clean" = every leg measured, "fail:<leg>"
# = that leg errored this pass). It writes the real artifact shapes the
# watcher + bench_state consume. The `if False` block carries literal
# run("...") lines so the REAL bench_state.expected_legs() regex derives
# the leg list from this stub, exactly as it does from the real bench.py.
FAKE_BENCH = '''
import json, os, sys

if False:
    run("leg_a")
    run("leg_b")
    run("leg_c")

LEGS = ["leg_a", "leg_b", "leg_c"]
quick = "--quick" in sys.argv

with open("BENCH_STUB") as f:
    plan = [l.strip() for l in f if l.strip()]
with open("BENCH_STUB_COUNT", "a") as f:
    f.write(("q" if quick else "F") + "\\n")
n_calls = sum(1 for _ in open("BENCH_STUB_COUNT"))
step = plan[min(n_calls - 1, len(plan) - 1)]

legs = {}
try:
    legs = json.load(open("BENCH_PARTIAL.json")).get("legs", {})
except Exception:
    pass
for leg in LEGS:
    if step == f"fail:{leg}":
        legs[leg] = {"error": "scripted failure"}
    else:
        cur = legs.get(leg)
        # mirror the real --fill semantics: re-measure missing/errored
        # rows always, and quick-only rows on a full-length pass
        stale = (not isinstance(cur, dict) or "error" in cur
                 or (not quick and cur.get("quick")))
        if stale:
            legs[leg] = {"value": 1.0, "quick": quick}
json.dump({"updated": "t", "legs": legs}, open("BENCH_PARTIAL.json", "w"))
print(json.dumps({"metric": "stub", "value": 1.0, "extras": legs}))
'''

FAKE_W2V = '''
import json, os
n = int(open("W2V_COUNT").read() or 0) if os.path.exists("W2V_COUNT") else 0
open("W2V_COUNT", "w").write(str(n + 1))
if os.environ.get("W2V_FAIL_FIRST") and n == 0:
    raise SystemExit(1)  # exits without writing the artifact
json.dump({"verdict": "stub"}, open("W2V_PROFILE.json", "w"))
print("{}")
'''


def _mk_harness(tmp_path, plan, env_extra=None):
    d = tmp_path / "repo"
    (d / "scripts").mkdir(parents=True)
    (d / "benchmarks").mkdir()
    (d / "jax").mkdir()
    (d / "jax" / "__init__.py").write_text(FAKE_JAX)
    (d / "jax" / "numpy.py").write_text(
        "class _A:\n"
        "    def sum(self):\n"
        "        return 2.0\n"
        "def ones(shape):\n"
        "    return _A()\n")
    (d / "bench.py").write_text(FAKE_BENCH)
    (d / "benchmarks" / "word2vec_profile.py").write_text(FAKE_W2V)
    (d / "BENCH_STUB").write_text("\n".join(plan))
    shutil.copy(os.path.join(REPO, "scripts", "bench_state.py"),
                d / "scripts" / "bench_state.py")
    script = d / "scripts" / "bench_watch.sh"
    shutil.copy(os.path.join(REPO, "scripts", "bench_watch.sh"), script)
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["BENCH_WATCH_DIR"] = str(d)
    env["BENCH_WATCH_AXON_SITE"] = str(d)  # no axon sitecustomize
    env.update(env_extra or {})
    return d, env


def _run(d, env, timeout=120):
    r = subprocess.run(["bash", str(d / "scripts" / "bench_watch.sh")],
                       env=env, capture_output=True, text=True,
                       timeout=timeout, cwd=str(d))
    log = (d / "bench_watch.log").read_text()
    return r, log


def test_happy_path_quick_full_w2v(tmp_path):
    d, env = _mk_harness(tmp_path, ["clean"])
    r, log = _run(d, env)
    assert r.returncode == 0, r.stderr[-500:]
    assert "quick pass 1" in log
    assert "-> full bench (attempt 1)" in log
    assert "word2vec device profile (attempt 1)" in log
    assert "capture complete" in log
    # artifacts: merged partial clean, full result captured, w2v present
    legs = json.load(open(d / "BENCH_PARTIAL.json"))["legs"]
    assert all("error" not in legs[k] for k in ("leg_a", "leg_b", "leg_c"))
    assert json.load(open(d / "BENCH_WATCH.json"))["metric"] == "stub"
    assert (d / "W2V_PROFILE.json").exists()
    assert (d / "BENCH_PARTIAL_QUICK.json").exists()
    # quick rows were re-measured at full length before the full check
    assert not legs["leg_a"].get("quick", False)
    # one quick + exactly one full pass sufficed (no wasted re-runs)
    calls = open(d / "BENCH_STUB_COUNT").read()
    assert calls.count("q") == 1 and calls.count("F") == 1, calls


def test_failed_leg_retries_then_completes(tmp_path):
    # pass 1 (quick): leg_b errors -> watcher must loop a SECOND quick
    # pass that fills the gap, then proceed full -> w2v -> complete
    d, env = _mk_harness(tmp_path, ["fail:leg_b", "clean"])
    r, log = _run(d, env)
    assert r.returncode == 0, r.stderr[-500:]
    assert "quick pass 1" in log and "quick pass 2" in log
    assert "capture complete" in log
    legs = json.load(open(d / "BENCH_PARTIAL.json"))["legs"]
    assert "error" not in legs["leg_b"]
    # the failing pass annotated, never clobbered, once measured
    calls = open(d / "BENCH_STUB_COUNT").read()
    assert calls.count("q") == 2 and calls.count("F") >= 1


def test_w2v_retry_on_missing_artifact(tmp_path):
    # w2v attempt 1 exits 0-adjacent (scripted rc=1, no artifact):
    # the watcher must re-arm and attempt again, then exit complete
    d, env = _mk_harness(tmp_path, ["clean"],
                         env_extra={"W2V_FAIL_FIRST": "1"})
    r, log = _run(d, env)
    assert r.returncode == 0, r.stderr[-500:]
    assert "word2vec device profile (attempt 1)" in log
    assert "w2v profile failed; re-arming" in log
    assert "word2vec device profile (attempt 2)" in log
    assert "capture complete" in log
    assert (d / "W2V_PROFILE.json").exists()
