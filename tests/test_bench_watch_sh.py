"""The tunnel watcher's state machine, executed for real.

scripts/bench_watch.sh is the round's critical capture machine, but its
quick->full->w2v path has never run live (the tunnel never stayed up).
This harness runs the ACTUAL script in a stub repo: a permissive fake
`jax` makes the probe succeed instantly (or fail while a TUNNEL_DOWN
marker exists, so outages can be scripted), a stub `bench.py` plays
scripted scenarios into the real artifact files, and the REAL
scripts/bench_state.py checker arbitrates completeness — so the shell
logic (gap-filling loop, per-contact-window caps, artifact-based w2v
retry, the never-exit re-arm contract) is what's under test, not
stand-ins for it.

Round-5 contract (VERDICT r4 weak #3): the watcher NEVER exits — a
complete capture idles and re-verifies; exhausted caps slow-re-arm with
fresh counters; every down->up transition resets the counters. Tests
therefore poll the log for state transitions and kill the watcher's
process group when done (the group kill itself is part of the contract:
ADVICE r4 #1 — the self-setsid must make `kill -- -pid` take children
down too)."""
import json
import os
import shutil
import signal
import stat
import subprocess
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_JAX = '''
"""Permissive jax stub: the watcher's PROBE only needs devices()[0]
.platform != 'cpu' and a summable ones((2,)); sitecustomize (if any)
touching other attributes gets inert callables. A TUNNEL_DOWN marker in
the stub repo root turns the device into a CPU fallback so tests can
script outages."""
import os as _os

_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))


class _Dev:
    @property
    def platform(self):
        if _os.path.exists(_os.path.join(_ROOT, "TUNNEL_DOWN")):
            return "cpu"
        return "stub-tpu"

    def __repr__(self):
        return "StubTPU"


def devices():
    return [_Dev()]


def __getattr__(name):
    class _Inert:
        def __call__(self, *a, **k):
            return self
        def __getattr__(self, n):
            return self
    return _Inert()
'''

# The stub bench plays a scenario from BENCH_STUB file: each line is one
# planned invocation outcome ("clean" = every leg measured, "fail:<leg>"
# = that leg errored this pass); the last line repeats forever. It writes
# the real artifact shapes the watcher + bench_state consume. The
# `if False` block carries literal run("...") lines so the REAL
# bench_state.expected_legs() regex derives the leg list from this stub,
# exactly as it does from the real bench.py.
FAKE_BENCH = '''
import json, os, sys

if False:
    run("leg_a")
    run("leg_b")
    run("leg_c")

LEGS = ["leg_a", "leg_b", "leg_c"]
quick = "--quick" in sys.argv

with open("BENCH_STUB") as f:
    plan = [l.strip() for l in f if l.strip()]
with open("BENCH_STUB_COUNT", "a") as f:
    f.write(("q" if quick else "F") + "\\n")
n_calls = sum(1 for _ in open("BENCH_STUB_COUNT"))
step = plan[min(n_calls - 1, len(plan) - 1)]

legs = {}
try:
    legs = json.load(open("BENCH_PARTIAL.json")).get("legs", {})
except Exception:
    pass
out = {}
for leg in LEGS:
    if step == f"fail:{leg}":
        # mirror the real merge semantics (_persist_partial): an error
        # row ANNOTATES a measured row, never clobbers it — but the
        # pass's own stdout (what the watcher redirects into
        # BENCH_WATCH*.json) carries the error row
        out[leg] = {"error": "scripted failure"}
        cur = legs.get(leg)
        if isinstance(cur, dict) and "error" not in cur:
            cur = dict(cur)
            cur["last_error"] = "scripted failure"
            legs[leg] = cur
        else:
            legs[leg] = out[leg]
    else:
        cur = legs.get(leg)
        # mirror the real --fill semantics: re-measure missing/errored
        # rows always, and quick-only rows on a full-length pass
        stale = (not isinstance(cur, dict) or "error" in cur
                 or (not quick and cur.get("quick")))
        if stale:
            legs[leg] = {"value": 1.0, "quick": quick}
        out[leg] = legs[leg]
json.dump({"updated": "t", "legs": legs}, open("BENCH_PARTIAL.json", "w"))
print(json.dumps({"metric": "stub", "value": 1.0, "extras": out}))
'''

FAKE_W2V = '''
import json, os
n = int(open("W2V_COUNT").read() or 0) if os.path.exists("W2V_COUNT") else 0
open("W2V_COUNT", "w").write(str(n + 1))
if os.environ.get("W2V_FAIL_FIRST") and n == 0:
    raise SystemExit(1)  # exits without writing the artifact
json.dump({"verdict": "stub"}, open("W2V_PROFILE.json", "w"))
print("{}")
'''


def _mk_harness(tmp_path, plan, env_extra=None, tunnel_down=False):
    d = tmp_path / "repo"
    (d / "scripts").mkdir(parents=True)
    (d / "benchmarks").mkdir()
    (d / "jax").mkdir()
    (d / "jax" / "__init__.py").write_text(FAKE_JAX)
    (d / "jax" / "numpy.py").write_text(
        "class _A:\n"
        "    def sum(self):\n"
        "        return 2.0\n"
        "def ones(shape):\n"
        "    return _A()\n")
    (d / "bench.py").write_text(FAKE_BENCH)
    (d / "benchmarks" / "word2vec_profile.py").write_text(FAKE_W2V)
    (d / "BENCH_STUB").write_text("\n".join(plan))
    if tunnel_down:
        (d / "TUNNEL_DOWN").write_text("")
    shutil.copy(os.path.join(REPO, "scripts", "bench_state.py"),
                d / "scripts" / "bench_state.py")
    script = d / "scripts" / "bench_watch.sh"
    shutil.copy(os.path.join(REPO, "scripts", "bench_watch.sh"), script)
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["BENCH_WATCH_DIR"] = str(d)
    env["BENCH_WATCH_AXON_SITE"] = str(d)  # no axon sitecustomize
    # short (integer — the chunked re-arm wait uses shell arithmetic)
    # sleeps: the state machine under test is the same; only the waits
    # shrink
    env["BENCH_WATCH_POLL"] = "1"
    env["BENCH_WATCH_REARM"] = "2"
    env.update(env_extra or {})
    return d, env


def _spawn(d, env):
    return subprocess.Popen(
        ["bash", str(d / "scripts" / "bench_watch.sh")],
        env=env, cwd=str(d),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _log(d) -> str:
    try:
        return (d / "bench_watch.log").read_text()
    except OSError:
        return ""


def _wait_log(d, predicate, timeout=90, what=""):
    t0 = time.time()
    while time.time() - t0 < timeout:
        log = _log(d)
        if predicate(log):
            return log
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}; log:\n{_log(d)[-3000:]}")


def _kill(proc, d):
    """Group kill via the pidfile — the production stop recipe."""
    try:
        pid = int((d / ".bench_watch.pid").read_text())
        os.killpg(pid, signal.SIGKILL)
    except (OSError, ValueError, ProcessLookupError):
        pass
    try:
        proc.kill()
    except OSError:
        pass
    proc.wait(timeout=10)


def test_happy_path_quick_full_w2v_then_idle(tmp_path):
    d, env = _mk_harness(tmp_path, ["clean"])
    proc = _spawn(d, env)
    try:
        log = _wait_log(d, lambda l: "capture complete" in l,
                        what="capture complete")
        assert "quick pass 1" in log
        assert "-> full bench (attempt 1)" in log
        assert "word2vec device profile (attempt 1)" in log
        # artifacts: merged partial clean, full result captured, w2v present
        legs = json.load(open(d / "BENCH_PARTIAL.json"))["legs"]
        assert all("error" not in legs[k] for k in ("leg_a", "leg_b", "leg_c"))
        assert json.load(open(d / "BENCH_WATCH.json"))["metric"] == "stub"
        assert (d / "W2V_PROFILE.json").exists()
        assert (d / "BENCH_PARTIAL_QUICK.json").exists()
        # quick rows were re-measured at full length before the full check
        assert not legs["leg_a"].get("quick", False)
        # one quick + exactly one full pass sufficed (no wasted re-runs)
        calls = open(d / "BENCH_STUB_COUNT").read()
        assert calls.count("q") == 1 and calls.count("F") == 1, calls
        # NEVER-exit contract: completion idles, it does not exit
        _wait_log(d, lambda l: l.count("capture complete") >= 2,
                  what="second idle re-verify")
        assert proc.poll() is None, "watcher exited after capture"
        # self-setsid made the watcher a process-group leader, so the
        # pidfile group kill can reap in-flight children (ADVICE r4 #1).
        # Only asserted where setsid exists — the script's documented
        # fallback is to run without leadership on hosts lacking it.
        if shutil.which("setsid"):
            pid = int((d / ".bench_watch.pid").read_text())
            pgid = subprocess.run(["ps", "-o", "pgid=", "-p", str(pid)],
                                  capture_output=True, text=True).stdout.strip()
            assert pgid == str(pid), \
                f"watcher is not its own group leader ({pgid})"
    finally:
        _kill(proc, d)


def test_failed_leg_retries_then_completes(tmp_path):
    # pass 1 (quick): leg_b errors -> watcher must loop a SECOND quick
    # pass that fills the gap, then proceed full -> w2v -> complete
    d, env = _mk_harness(tmp_path, ["fail:leg_b", "clean"])
    proc = _spawn(d, env)
    try:
        log = _wait_log(d, lambda l: "capture complete" in l,
                        what="capture complete")
        assert "quick pass 1" in log and "quick pass 2" in log
        legs = json.load(open(d / "BENCH_PARTIAL.json"))["legs"]
        assert "error" not in legs["leg_b"]
        # the failing pass annotated, never clobbered, once measured
        calls = open(d / "BENCH_STUB_COUNT").read()
        assert calls.count("q") == 2 and calls.count("F") >= 1
    finally:
        _kill(proc, d)


def test_w2v_retry_on_missing_artifact(tmp_path):
    # w2v attempt 1 exits 0-adjacent (scripted rc=1, no artifact):
    # the watcher must re-arm and attempt again, then reach complete
    d, env = _mk_harness(tmp_path, ["clean"],
                         env_extra={"W2V_FAIL_FIRST": "1"})
    proc = _spawn(d, env)
    try:
        log = _wait_log(d, lambda l: "capture complete" in l,
                        what="capture complete")
        assert "word2vec device profile (attempt 1)" in log
        assert "w2v profile failed; re-arming" in log
        assert "word2vec device profile (attempt 2)" in log
        assert (d / "W2V_PROFILE.json").exists()
    finally:
        _kill(proc, d)


def test_cap_exhaustion_slow_rearms_instead_of_exiting(tmp_path):
    # VERDICT r4 weak #3: leg_b fails DETERMINISTICALLY. One contact
    # window burns its 5 quick + 3 full passes, then the watcher must
    # slow-re-arm with fresh counters and keep trying — never exit.
    d, env = _mk_harness(tmp_path, ["fail:leg_b"])
    proc = _spawn(d, env)
    try:
        log = _wait_log(
            d, lambda l: l.count("window caps exhausted") >= 2,
            what="two slow re-arms")
        # counters were reset between the windows: quick pass 1 ran again
        assert log.count("quick pass 1 ") >= 2, log[-2000:]
        assert proc.poll() is None, "watcher exited on cap exhaustion"
        calls = open(d / "BENCH_STUB_COUNT").read()
        # per-window budget honored (5 quick / 3 full per window), and a
        # second window actually spent a fresh budget
        assert calls.count("q") >= 10 and calls.count("F") >= 6, calls
    finally:
        _kill(proc, d)


def test_quick_only_capture_is_not_complete(tmp_path):
    # Quick rows fill BENCH_PARTIAL (clean), but every FULL-length pass
    # fails: the terminal state must be the honest "caps exhausted", not
    # "capture complete" — reduced-step --quick numbers are not a
    # finished capture (the full artifact check gates the done-signal).
    d, env = _mk_harness(tmp_path, ["clean", "fail:leg_b"])
    proc = _spawn(d, env)
    try:
        log = _wait_log(d, lambda l: "window caps exhausted" in l,
                        what="exhausted window")
        assert "capture complete" not in log
        # the quick row survived the failing full passes (annotate, not
        # clobber) and records what went wrong
        legs = json.load(open(d / "BENCH_PARTIAL.json"))["legs"]
        assert "error" not in legs["leg_b"] and legs["leg_b"]["quick"]
        assert legs["leg_b"]["last_error"] == "scripted failure"
        calls = open(d / "BENCH_STUB_COUNT").read()
        # full cap honored (>= because a follow-up re-armed window may
        # already be spending its own budget by the time we read this)
        assert calls.count("F") >= 3 and calls.count("q") == 1, calls
    finally:
        _kill(proc, d)


def test_startup_takes_over_live_incumbent(tmp_path):
    # A duplicate watcher under the never-exit contract would run forever
    # (double bench load, artifact races) with its pid lost the moment
    # the new watcher overwrites the pidfile — startup must kill a live
    # incumbent named by the pidfile first. The stand-in process carries
    # "scripts/bench_watch.sh" in argv[0] so the tightened /proc cmdline
    # identity check (script path, not the bare substring — ADVICE r5)
    # recognizes it.
    d, env = _mk_harness(tmp_path, ["clean"])
    dummy = subprocess.Popen(
        ["bash", "-c", "exec -a scripts/bench_watch.sh sleep 300"])
    (d / ".bench_watch.pid").write_text(str(dummy.pid))
    proc = _spawn(d, env)
    try:
        _wait_log(d, lambda l: "killing incumbent watcher" in l,
                  what="takeover log line")
        assert dummy.wait(timeout=15) != 0  # incumbent was killed
        _wait_log(d, lambda l: "capture complete" in l,
                  what="new watcher proceeds to capture")
        assert int((d / ".bench_watch.pid").read_text()) != dummy.pid
    finally:
        dummy.poll() or dummy.kill()
        _kill(proc, d)


def test_stale_pidfile_of_dead_process_is_ignored(tmp_path):
    # A dead incumbent (or a recycled pid now naming a non-watcher
    # process) must NOT trigger the takeover kill.
    d, env = _mk_harness(tmp_path, ["clean"])
    innocent = subprocess.Popen(["sleep", "300"])
    (d / ".bench_watch.pid").write_text(str(innocent.pid))
    proc = _spawn(d, env)
    try:
        _wait_log(d, lambda l: "capture complete" in l, what="capture")
        assert innocent.poll() is None, "non-watcher process was killed"
        assert "killing incumbent watcher" not in _log(d)
    finally:
        innocent.kill()
        _kill(proc, d)


def test_takeover_ignores_bare_substring_impostor(tmp_path):
    # The restart wrapper shell's argv contains 'bench_watch' (CLAUDE.md's
    # pkill trap) but NOT the script path — the tightened identity grep
    # (scripts/bench_watch.sh, ADVICE r5) must leave a recycled pid that
    # landed on such a process alone.
    d, env = _mk_harness(tmp_path, ["clean"])
    impostor = subprocess.Popen(
        ["bash", "-c", "exec -a bench_watch sleep 300"])
    (d / ".bench_watch.pid").write_text(str(impostor.pid))
    proc = _spawn(d, env)
    try:
        _wait_log(d, lambda l: "capture complete" in l, what="capture")
        assert impostor.poll() is None, "bare-substring impostor was killed"
        assert "killing incumbent watcher" not in _log(d)
    finally:
        impostor.kill()
        _kill(proc, d)


def test_round_guard_spawner_identity(monkeypatch, tmp_path):
    # bench._round_is_stale: the spawner-identity signal (BENCH_WATCH_ROUND
    # exported by the watcher) must catch a zombie spawner even though a
    # freshly spawned child is always younger than the marker.
    import sys
    sys.path.insert(0, REPO)
    import bench

    marker = tmp_path / ".bench_round_start"
    marker.write_text("")
    monkeypatch.setattr(bench, "_ROUND_MARKER", str(marker))
    monkeypatch.setattr(bench, "_START_TS", time.time())
    mt = int(os.path.getmtime(str(marker)))
    # same round id -> not stale (signal 2 also passes: marker older)
    monkeypatch.setenv("BENCH_WATCH_ROUND", str(mt))
    assert not bench._round_is_stale()
    # zombie spawner: inherited id predates the current marker -> stale
    monkeypatch.setenv("BENCH_WATCH_ROUND", str(mt - 5))
    assert bench._round_is_stale()
    # garbled id -> fail safe (stale)
    monkeypatch.setenv("BENCH_WATCH_ROUND", "not-a-number")
    assert bench._round_is_stale()
    # no watcher in the ancestry (manual run) -> signal 2 only
    monkeypatch.delenv("BENCH_WATCH_ROUND")
    assert not bench._round_is_stale()
    monkeypatch.setattr(bench, "_START_TS", mt - 100)
    assert bench._round_is_stale()


def test_flapping_tunnel_resets_counters_per_contact(tmp_path):
    # Five short windows separated by outages must each get a FRESH pass
    # budget (per-lifetime caps would leave window 2+ unwatched), and the
    # watcher must still be polling afterwards.
    d, env = _mk_harness(tmp_path, ["fail:leg_b"], tunnel_down=True)
    proc = _spawn(d, env)
    CONTACT = "tunnel contact: new window, pass counters reset"
    try:
        _wait_log(d, lambda l: "tunnel down" in l, what="initial outage")
        for i in range(1, 6):
            # strictly-new-event waits: cumulative counts can be inflated
            # by an extra poll cycle the 1-core host squeezed in, which
            # would pre-satisfy a later iteration's absolute threshold
            # and desynchronize the toggle from the watcher's real state
            base = _log(d)
            (d / "TUNNEL_DOWN").unlink()
            _wait_log(d, lambda l: l.count(CONTACT) > base.count(CONTACT),
                      what=f"contact #{i}")
            _wait_log(d, lambda l: l.count("quick pass 1 ") >
                      base.count("quick pass 1 "),
                      what=f"fresh quick budget in window #{i}")
            mid = _log(d)
            (d / "TUNNEL_DOWN").write_text("")
            _wait_log(d, lambda l: l.count("tunnel down") >
                      mid.count("tunnel down"),
                      what=f"outage #{i + 1}")
        assert proc.poll() is None, "watcher died during flapping windows"
        assert _log(d).count(CONTACT) >= 5
    finally:
        _kill(proc, d)
