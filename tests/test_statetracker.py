"""StateTracker / work-router / registry tests — mirrors the reference's
in-process actor tests (WorkerActorTest, TestDistributed) and the
heartbeat/job-reclaim semantics of the Hazelcast StateTracker."""

import threading
import time

import pytest

from deeplearning4j_tpu.parallel.statetracker import (
    FileServiceRegistry,
    HogwildWorkRouter,
    IterativeReduceWorkRouter,
    Job,
    StateTracker,
)


class TestStateTracker:
    def test_job_lifecycle(self):
        t = StateTracker()
        t.add_job(Job("j1", payload=41))
        job = t.request_job("w0")
        assert job.job_id == "j1" and job.worker_id == "w0"
        t.complete_job("j1", result=42)
        assert t.counts() == {"pending": 0, "assigned": 0, "done": 1}
        assert t.results()["j1"] == 42

    def test_failed_job_requeued(self):
        t = StateTracker()
        t.add_job(Job("j1", payload=1))
        t.request_job("w0")
        t.fail_job("j1")
        assert t.counts()["pending"] == 1
        job = t.request_job("w1")
        assert job.attempts == 2

    def test_heartbeat_expiry_and_reclaim(self):
        t = StateTracker(heartbeat_timeout=0.05)
        t.add_job(Job("j1", payload=1))
        t.request_job("w0")  # w0 takes the job and then dies
        time.sleep(0.12)
        assert "w0" in t.dead_workers()
        assert t.reclaim_dead_jobs() == 1
        assert t.counts()["pending"] == 1
        # a live worker keeps its job
        t.add_job(Job("j2", payload=2))
        t.request_job("w1")
        t.heartbeat("w1")
        assert t.reclaim_dead_jobs() == 0 or "w1" not in t.dead_workers()

    def test_param_storage(self):
        t = StateTracker()
        t.set_params("model", [1.0, 2.0])
        assert t.get_params("model") == [1.0, 2.0]


class TestRouters:
    def test_hogwild_processes_all_jobs(self):
        t = StateTracker()
        for i in range(20):
            t.add_job(Job(f"j{i}", payload=i))
        results = HogwildWorkRouter(t, num_workers=4).run(lambda x: x * x)
        assert len(results) == 20
        assert results["j7"] == 49

    def test_hogwild_retries_then_gives_up(self):
        t = StateTracker()
        t.add_job(Job("bad", payload=-1))
        calls = []

        def work(x):
            calls.append(x)
            raise RuntimeError("boom")

        results = HogwildWorkRouter(t, num_workers=1).run(work)
        assert len(calls) == 3  # 3 attempts
        assert "bad" in results and results["bad"] is None  # recorded poison
        assert t.counts()["pending"] == 0  # never re-queued after give-up

    def test_poison_job_does_not_starve_good_jobs(self):
        t = StateTracker()
        t.add_job(Job("bad", payload=-1))
        for i in range(10):
            t.add_job(Job(f"g{i}", payload=i))

        def work(x):
            if x < 0:
                raise RuntimeError("boom")
            return x

        results = HogwildWorkRouter(t, num_workers=2).run(work)
        assert sum(1 for k in results if k.startswith("g")) == 10

    def test_iterative_reduce_rounds_do_not_leak(self):
        t = StateTracker()
        router = IterativeReduceWorkRouter(t, num_workers=2)
        for i in range(4):
            t.add_job(Job(f"a{i}", payload=1.0))
        r1 = router.run_round(lambda x: x, lambda rs: sum(rs))
        assert r1 == 4.0
        for i in range(4):
            t.add_job(Job(f"b{i}", payload=2.0))
        r2 = router.run_round(lambda x: x, lambda rs: sum(rs))
        assert r2 == 8.0  # round 1 results must not leak in

    def test_iterative_reduce_round(self):
        t = StateTracker()
        for i in range(8):
            t.add_job(Job(f"j{i}", payload=float(i)))
        merged = IterativeReduceWorkRouter(t, num_workers=4).run_round(
            lambda x: x + 1.0, lambda rs: sum(rs) / len(rs)
        )
        assert merged == pytest.approx(sum(range(1, 9)) / 8)
        assert t.get_params("merged") == merged


class TestRegistry:
    def test_register_retrieve_roundtrip(self, tmp_path):
        reg = FileServiceRegistry(str(tmp_path))
        reg.register("master", {"host": "10.0.0.1", "port": 9000})
        assert reg.retrieve("master")["port"] == 9000
        assert reg.list_services() == ["master"]
        reg.unregister("master")
        assert reg.retrieve("master") is None
