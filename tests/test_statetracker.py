"""StateTracker / work-router / registry tests — mirrors the reference's
in-process actor tests (WorkerActorTest, TestDistributed) and the
heartbeat/job-reclaim semantics of the Hazelcast StateTracker."""

import threading
import time

import pytest

from deeplearning4j_tpu.parallel.statetracker import (
    FileServiceRegistry,
    HogwildWorkRouter,
    IterativeReduceWorkRouter,
    Job,
    StateTracker,
)


class TestStateTracker:
    def test_job_lifecycle(self):
        t = StateTracker()
        t.add_job(Job("j1", payload=41))
        job = t.request_job("w0")
        assert job.job_id == "j1" and job.worker_id == "w0"
        t.complete_job("j1", result=42)
        assert t.counts() == {"pending": 0, "assigned": 0, "done": 1}
        assert t.results()["j1"] == 42

    def test_failed_job_requeued(self):
        t = StateTracker()
        t.add_job(Job("j1", payload=1))
        t.request_job("w0")
        t.fail_job("j1")
        assert t.counts()["pending"] == 1
        job = t.request_job("w1")
        assert job.attempts == 2

    def test_heartbeat_expiry_and_reclaim(self):
        t = StateTracker(heartbeat_timeout=0.05)
        t.add_job(Job("j1", payload=1))
        t.request_job("w0")  # w0 takes the job and then dies
        time.sleep(0.12)
        assert "w0" in t.dead_workers()
        assert t.reclaim_dead_jobs() == 1
        assert t.counts()["pending"] == 1
        # a live worker keeps its job
        t.add_job(Job("j2", payload=2))
        t.request_job("w1")
        t.heartbeat("w1")
        assert t.reclaim_dead_jobs() == 0 or "w1" not in t.dead_workers()

    def test_param_storage(self):
        t = StateTracker()
        t.set_params("model", [1.0, 2.0])
        assert t.get_params("model") == [1.0, 2.0]

    def test_poison_job_routed_to_dead_letter(self):
        """Satellite (ISSUE 6): fail_job stops re-queueing after
        max_attempts — the poison job lands in poisoned_jobs() instead
        of cycling forever."""
        t = StateTracker(max_attempts=2)
        t.add_job(Job("bad", payload=1))
        assert t.request_job("w0").attempts == 1
        assert t.fail_job("bad") is True  # attempt 1 < cap: re-queued
        assert t.counts()["pending"] == 1
        assert t.request_job("w0").attempts == 2
        assert t.fail_job("bad") is False  # cap hit: dead-letter
        assert t.counts()["pending"] == 0
        assert t.poisoned_jobs() == {"bad": 2}
        assert t.request_job("w1") is None  # never redelivered

    def test_reclaim_path_hits_dead_letter_cap_too(self):
        """A split whose executor keeps DYING (reclaim path, not
        JobFailed) must hit the same max_attempts cap — else it cycles
        until the round timeout instead of surfacing as poisoned."""
        t = StateTracker(heartbeat_timeout=0.03, max_attempts=2)
        t.add_job(Job("j", payload=1))
        for _ in range(2):  # two deliveries, two executor deaths
            assert t.request_job("doomed") is not None
            time.sleep(0.08)
            t.reclaim_dead_jobs()
        assert t.poisoned_jobs() == {"j": 2}
        assert t.counts()["pending"] == 0

    def test_unbounded_attempts_by_default(self):
        t = StateTracker()  # max_attempts=None: legacy behavior
        t.add_job(Job("j", payload=1))
        for _ in range(5):
            t.request_job("w0")
            assert t.fail_job("j") is True
        assert t.poisoned_jobs() == {}

    def test_fenced_completion_rejects_stale_attempt(self):
        """A zombie executor (job reclaimed + re-assigned underneath it)
        completes with a stale attempt number: rejected and audited —
        the no-double-count half of the fleet contract."""
        t = StateTracker(heartbeat_timeout=0.05)
        t.add_job(Job("j", payload=1))
        stale = t.request_job("zombie")  # attempts=1
        time.sleep(0.12)
        assert t.reclaim_dead_jobs() == 1
        fresh = t.request_job("survivor")  # attempts=2
        assert t.complete_job("j", "late", attempt=stale.attempts) is False
        assert t.stale_completions == 1
        assert t.complete_job("j", "good", attempt=fresh.attempts) is True
        assert t.results()["j"] == "good"

    def test_fenced_fail_job_cannot_yank_survivor_assignment(self):
        """A zombie's late JobFailed must not pop the survivor's live
        re-assignment (a third execution burning attempts toward the
        poison cap) — fail_job fences like complete_job."""
        t = StateTracker(heartbeat_timeout=0.05, max_attempts=5)
        t.add_job(Job("j", payload=1))
        stale = t.request_job("zombie")
        time.sleep(0.12)
        t.reclaim_dead_jobs()
        fresh = t.request_job("survivor")
        assert t.fail_job("j", attempt=stale.attempts) is False  # fenced
        assert t.counts()["assigned"] == 1  # survivor still holds it
        assert t.complete_job("j", "good", attempt=fresh.attempts) is True
        # legacy unfenced fail still works
        t.add_job(Job("k", payload=2))
        t.request_job("w")
        assert t.fail_job("k") is True

    def test_membership_epoch_join_leave_death(self):
        """The promoted membership authority: epoch bumps on join,
        announced departure (in-flight jobs re-queued immediately), and
        heartbeat-expiry death."""
        t = StateTracker(heartbeat_timeout=0.05)
        assert t.register_worker("a") == 1
        assert t.register_worker("b") == 2
        assert t.register_worker("a") == 2  # idempotent: no bump
        assert t.live_workers() == ["a", "b"]
        t.add_job(Job("j", payload=1))
        job = t.request_job("a")
        assert job is not None
        assert t.deregister_worker("a") == 3  # goodbye: job re-queued NOW
        assert t.counts()["pending"] == 1
        assert t.live_workers() == ["b"]
        time.sleep(0.12)  # b goes silent
        t.reclaim_dead_jobs()
        assert t.live_workers() == []
        assert t.membership() == {"epoch": 4, "workers": []}


class TestRouters:
    def test_hogwild_processes_all_jobs(self):
        t = StateTracker()
        for i in range(20):
            t.add_job(Job(f"j{i}", payload=i))
        results = HogwildWorkRouter(t, num_workers=4).run(lambda x: x * x)
        assert len(results) == 20
        assert results["j7"] == 49

    def test_hogwild_retries_then_gives_up(self):
        t = StateTracker()
        t.add_job(Job("bad", payload=-1))
        calls = []

        def work(x):
            calls.append(x)
            raise RuntimeError("boom")

        results = HogwildWorkRouter(t, num_workers=1).run(work)
        assert len(calls) == 3  # 3 attempts
        assert "bad" in results and results["bad"] is None  # recorded poison
        assert t.counts()["pending"] == 0  # never re-queued after give-up

    def test_poison_job_does_not_starve_good_jobs(self):
        t = StateTracker()
        t.add_job(Job("bad", payload=-1))
        for i in range(10):
            t.add_job(Job(f"g{i}", payload=i))

        def work(x):
            if x < 0:
                raise RuntimeError("boom")
            return x

        results = HogwildWorkRouter(t, num_workers=2).run(work)
        assert sum(1 for k in results if k.startswith("g")) == 10

    def test_iterative_reduce_rounds_do_not_leak(self):
        t = StateTracker()
        router = IterativeReduceWorkRouter(t, num_workers=2)
        for i in range(4):
            t.add_job(Job(f"a{i}", payload=1.0))
        r1 = router.run_round(lambda x: x, lambda rs: sum(rs))
        assert r1 == 4.0
        for i in range(4):
            t.add_job(Job(f"b{i}", payload=2.0))
        r2 = router.run_round(lambda x: x, lambda rs: sum(rs))
        assert r2 == 8.0  # round 1 results must not leak in

    def test_iterative_reduce_round(self):
        t = StateTracker()
        for i in range(8):
            t.add_job(Job(f"j{i}", payload=float(i)))
        merged = IterativeReduceWorkRouter(t, num_workers=4).run_round(
            lambda x: x + 1.0, lambda rs: sum(rs) / len(rs)
        )
        assert merged == pytest.approx(sum(range(1, 9)) / 8)
        assert t.get_params("merged") == merged


class TestRegistry:
    def test_register_retrieve_roundtrip(self, tmp_path):
        reg = FileServiceRegistry(str(tmp_path))
        reg.register("master", {"host": "10.0.0.1", "port": 9000})
        assert reg.retrieve("master")["port"] == 9000
        assert reg.list_services() == ["master"]
        reg.unregister("master")
        assert reg.retrieve("master") is None


# -------------------------------------------------- cross-process protocol
WORKER_SCRIPT = r"""
import sys, time
from deeplearning4j_tpu.parallel.statetracker import RemoteStateTracker

address, worker_id, mode = sys.argv[1], sys.argv[2], sys.argv[3]
t = RemoteStateTracker.from_address(address)
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    job = t.request_job(worker_id)
    if job is None:
        t.heartbeat(worker_id)
        time.sleep(0.05)
        continue
    if mode == "hang":
        # take the job, then die silently holding it (no heartbeat, no
        # complete) — the failure the reclaim protocol must detect
        time.sleep(3600)
    time.sleep(job.payload.get("work_s", 0))
    t.complete_job(job.job_id, {"worker": worker_id,
                                "value": job.payload["n"] * 2})
"""


class TestCrossProcess:
    """The reference Hazelcast plane is multi-process
    (BaseHazelCastStateTracker.java:49); these tests run the queue/
    heartbeat/reclaim protocol against REAL worker subprocesses over the
    TCP transport, including a worker kill + job reclaim."""

    @pytest.fixture()
    def server(self):
        from deeplearning4j_tpu.parallel.statetracker import (
            StateTrackerServer,
        )

        tracker = StateTracker(heartbeat_timeout=1.0)
        srv = StateTrackerServer(tracker).start()
        yield srv
        srv.stop()

    def _spawn(self, tmp_path, address, worker_id, mode="work"):
        import os
        import subprocess
        import sys

        script = tmp_path / "worker.py"
        if not script.exists():
            script.write_text(WORKER_SCRIPT)
        env = dict(os.environ)
        env["PYTHONPATH"] = "/root/repo:" + env.get("PYTHONPATH", "")
        return subprocess.Popen(
            [sys.executable, str(script), address, worker_id, mode],
            env=env)

    def _wait(self, cond, timeout=20.0, step=0.1):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(step)
        return False

    def test_two_subprocess_workers_complete_all_jobs(self, server,
                                                      tmp_path):
        procs = [self._spawn(tmp_path, server.address, f"w{i}")
                 for i in range(2)]
        try:
            # both processes up (idle workers heartbeat) BEFORE work exists,
            # and each job takes real time — else on this 1-core host the
            # first worker drains the queue before the second even starts
            assert self._wait(
                lambda: len(server.tracker._heartbeats) == 2)
            for i in range(8):
                server.tracker.add_job(Job(f"job-{i}",
                                           {"n": i, "work_s": 0.25}))
            assert self._wait(
                lambda: server.tracker.counts()["done"] == 8), \
                server.tracker.counts()
            results = server.tracker.results()
            assert {r["value"] for r in results.values()} == {
                2 * i for i in range(8)}
            # both processes actually participated
            assert len({r["worker"] for r in results.values()}) == 2
        finally:
            for p in procs:
                p.kill()
                p.wait()

    def test_killed_worker_job_reclaimed_and_finished(self, server,
                                                      tmp_path):
        """Kill a worker holding a job: after heartbeat expiry the master
        reclaims it and a surviving worker completes it (the ClearWorker
        protocol the reference gets from Hazelcast membership)."""
        server.tracker.add_job(Job("job-a", {"n": 1}))
        hang = self._spawn(tmp_path, server.address, "hangw", mode="hang")
        try:
            assert self._wait(
                lambda: server.tracker.counts()["assigned"] == 1)
            hang.kill()
            hang.wait()
            # dead worker's heartbeat must expire, then reclaim re-queues
            assert self._wait(
                lambda: "hangw" in server.tracker.dead_workers(),
                timeout=5)
            assert server.tracker.reclaim_dead_jobs() == 1
            good = self._spawn(tmp_path, server.address, "goodw")
            try:
                assert self._wait(
                    lambda: server.tracker.counts()["done"] == 1)
                res = server.tracker.results()["job-a"]
                assert res == {"worker": "goodw", "value": 2}
                # second delivery is recorded (attempts incremented)
                assert server.tracker._done["job-a"].attempts == 2
            finally:
                good.kill()
                good.wait()
        finally:
            if hang.poll() is None:
                hang.kill()
                hang.wait()

    def test_remote_membership_and_dead_letter_surface(self, server):
        """The fleet's membership + dead-letter protocol over the TCP
        transport (the promoted tracker is the cross-process membership
        authority)."""
        from deeplearning4j_tpu.parallel.statetracker import (
            RemoteStateTracker,
        )

        server.tracker.max_attempts = 1
        t = RemoteStateTracker.from_address(server.address)
        try:
            assert t.register_worker("rw0") == 1
            assert t.live_workers() == ["rw0"]
            assert t.membership() == {"epoch": 1, "workers": ["rw0"]}
            t.add_job(Job("j", {"n": 1}))
            job = t.request_job("rw0")
            # fenced completion over the wire: stale attempt rejected
            assert t.complete_job("j", {"v": 1},
                                  attempt=job.attempts + 1) is False
            assert t.complete_job("j", {"v": 1},
                                  attempt=job.attempts) is True
            t.add_job(Job("poison", {"n": 2}))
            t.request_job("rw0")
            assert t.fail_job("poison") is False  # max_attempts=1
            assert t.poisoned_jobs() == {"poison": 1}
            assert t.deregister_worker("rw0") == 2
            assert t.live_workers() == []
        finally:
            t.close()

    def test_remote_params_and_errors(self, server):
        from deeplearning4j_tpu.parallel.statetracker import (
            RemoteStateTracker,
        )

        t = RemoteStateTracker.from_address(server.address)
        try:
            t.set_params("merged", [1.5, 2.5])
            assert t.get_params("merged") == [1.5, 2.5]
            assert t.counts()["pending"] == 0
            with pytest.raises(RuntimeError, match="unknown method"):
                t._call("no_such_method")
        finally:
            t.close()


    def test_non_json_result_yields_error_reply_not_dead_connection(
            self, server):
        import numpy as np

        from deeplearning4j_tpu.parallel.statetracker import (
            RemoteStateTracker,
        )

        server.tracker.set_params("merged", np.arange(3))  # in-process router
        t = RemoteStateTracker.from_address(server.address)
        try:
            with pytest.raises(RuntimeError, match="not JSON-serializable"):
                t.get_params("merged")
            # connection survives: next call still works
            assert t.counts()["pending"] == 0
        finally:
            t.close()

    def test_timeout_poisons_connection(self, server):
        from deeplearning4j_tpu.parallel.statetracker import (
            RemoteStateTracker,
        )

        t = RemoteStateTracker.from_address(server.address, timeout=0.2)
        try:
            # stall the server so the reply misses the client deadline
            orig = server.tracker.counts
            server.tracker.counts = lambda: (time.sleep(0.6), orig())[1]
            with pytest.raises(OSError):
                t.counts()
            server.tracker.counts = orig
            # the connection is now poisoned, not silently desynced
            with pytest.raises(ConnectionError, match="broken"):
                t.heartbeat("w")
        finally:
            server.tracker.counts = orig
            t.close()
