"""Online learning loop (deeplearning4j_tpu/online/) — ISSUE 14.

Quick-tier contracts:

  (a) training KILLED at stream offset k and RESUMED through a live
      StreamSource produces bit-identical params and loss curve to the
      uninterrupted run — the delivered-batch cursor IS the stream
      offset (Kafka committed-offset replay).
  (b) a COMPLETED promotion serves the candidate with zero
      dropped/failed admitted requests during the swap; an INJECTED
      warmup failure leaves the prior default serving with the
      candidate broken (PR 8 isolation, never moving the default).
  (c) a scripted distribution shift fires the drift alarm
      deterministically and BLOCKS promotion.
  (d) shadow mirroring on => client-visible /predict outputs
      byte-identical to mirroring off.

Plus the ISSUE 14 satellites: registry version lineage
(prior_default/lineage/rollback_target + /models exposure) and the
promotion races (drain mid-shadow seals the lifecycle without promoting;
a failing shadow model never votes the primary's breaker).
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import DataSet
from deeplearning4j_tpu.etl.normalize import NormalizerStandardize
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.online import (
    ContinuousTrainer,
    DriftMonitor,
    PromotionRefused,
    ShadowPromoter,
    StreamBackpressure,
    StreamClosed,
    StreamSource,
)
from deeplearning4j_tpu.resilience import (
    ChaosConfig,
    ChaosMonkey,
    CheckpointManager,
    InjectedKill,
)
from deeplearning4j_tpu.resilience.chaos import (
    InjectedServingFault,
    ServingChaos,
    ServingChaosConfig,
)
from deeplearning4j_tpu.serving.engine import ServingEngine
from deeplearning4j_tpu.serving.resilience import DrainingError
from deeplearning4j_tpu.utils.serialization import ModelSerializer

_RNG = np.random.default_rng(0)
X = _RNG.standard_normal((96, 6)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[_RNG.integers(0, 3, 96)]


def build_net(seed=7) -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def push_all(src: StreamSource, upto: int = 96, batch: int = 8) -> int:
    n = 0
    for i in range(0, upto, batch):
        src.push(DataSet(X[i:i + batch], Y[i:i + batch]))
        n += 1
    return n


def params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def fitted_norm() -> NormalizerStandardize:
    return NormalizerStandardize().fit(X)


# ---------------------------------------------------------------------------
# StreamSource semantics
# ---------------------------------------------------------------------------


class TestStreamSource:
    def test_offsets_monotone_and_in_order(self):
        src = StreamSource(watermark=32, idle_s=0.05)
        offs = [src.push(DataSet(X[i:i + 8], Y[i:i + 8]))
                for i in range(0, 32, 8)]
        assert offs == [0, 1, 2, 3]
        got = list(src)  # one poll window drains the backlog then idles
        assert len(got) == 4
        np.testing.assert_array_equal(np.asarray(got[0].features), X[:8])
        assert src.state() == {"offset": 4}
        assert list(src) == []  # idle window: empty pass, cursor keeps

    def test_backpressure_blocks_then_raises(self):
        src = StreamSource(watermark=2, idle_s=0.05)
        push_all(src, upto=16)  # fills the 2-batch watermark
        t0 = time.monotonic()
        with pytest.raises(StreamBackpressure):
            src.push(DataSet(X[:8], Y[:8]), timeout_s=0.2)
        assert time.monotonic() - t0 >= 0.15
        # delivering frees headroom: the next push admits immediately
        assert len(list(src)) == 2
        assert src.push(DataSet(X[:8], Y[:8]), timeout_s=1.0) == 2

    def test_close_drains_then_refuses(self):
        src = StreamSource(watermark=8, idle_s=10.0)  # long idle: close ends
        push_all(src, upto=16)
        src.close()
        assert len(list(src)) == 2  # buffered batches still deliver
        with pytest.raises(StreamClosed):
            src.push(DataSet(X[:8], Y[:8]))

    def test_restore_state_seeks(self):
        src = StreamSource(watermark=32, idle_s=0.05)
        push_all(src, upto=32)
        src.restore_state({"offset": 2})
        got = list(src)
        assert len(got) == 2  # offsets 0,1 dropped as already-consumed
        np.testing.assert_array_equal(np.asarray(got[0].features), X[16:24])


# ---------------------------------------------------------------------------
# Contract (a): kill at stream offset k + resume == uninterrupted
# ---------------------------------------------------------------------------


class TestKillResumeThroughStream:
    def _run(self, manager, *, chaos=None, prefill=96):
        src = StreamSource(watermark=64, idle_s=0.1)
        push_all(src, upto=prefill)
        ct = ContinuousTrainer(build_net(), src, manager=manager,
                               workers=1, shard=None, chaos=chaos,
                               handle_signals=False)
        ct.fit_round()
        return ct

    def test_kill_resume_bit_exact(self, tmp_path):
        baseline = self._run(None)
        assert baseline.step == 12

        mgr = CheckpointManager(str(tmp_path), every_steps=4, keep_last=3)
        with pytest.raises(InjectedKill):
            self._run(mgr, chaos=ChaosMonkey(ChaosConfig(kill_at_step=6)))
        mgr.close()

        # resume: FRESH process shape — new net, new source, the producer
        # re-pushes from the committed offset (restore_state drops below)
        mgr2 = CheckpointManager(str(tmp_path), every_steps=4, keep_last=3)
        resumed = self._run(mgr2)
        mgr2.close()

        assert resumed.resilient.resumed_step == 4  # checkpoint at step 4
        assert resumed.step == baseline.step
        assert params_equal(baseline.net.params, resumed.net.params)
        assert params_equal(baseline.net.updater_state,
                            resumed.net.updater_state)
        stitched = (baseline.losses[:resumed.resilient.resumed_step]
                    + resumed.losses)
        assert stitched == baseline.losses, "loss curve diverged"

    def test_cursor_survives_empty_round(self, tmp_path):
        """An idle poll window (zero batches) must not move the committed
        offset or spam checkpoints — the next data round continues."""
        mgr = CheckpointManager(str(tmp_path), every_steps=4, keep_last=3)
        src = StreamSource(watermark=64, idle_s=0.05)
        ct = ContinuousTrainer(build_net(), src, manager=mgr,
                               workers=1, shard=None, handle_signals=False)
        push_all(src, upto=32)
        assert len(ct.fit_round()) == 4
        assert ct.fit_round() == []          # idle window, empty round
        assert ct.rounds_done == 1           # not counted
        push_all(src, upto=32)
        assert len(ct.fit_round()) == 4
        assert ct.step == 8
        mgr.close()


# ---------------------------------------------------------------------------
# Contracts (b)+(d) and the promotion races
# ---------------------------------------------------------------------------


def serving_net(seed=7) -> MultiLayerNetwork:
    net = build_net(seed).init()
    net.fit(X[:32], Y[:32])
    return net


@pytest.fixture()
def candidate_zip(tmp_path):
    path = str(tmp_path / "candidate.zip")
    ModelSerializer.write_model(serving_net(11), path,
                                normalizer=fitted_norm())
    return path


class TestShadowPromotion:
    def test_mirroring_on_is_byte_invisible(self, candidate_zip):
        """Contract (d): the same rows answer byte-identically with the
        mirror attached vs not — shadow answers never reach clients."""
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            rows = [X[i:i + 8] for i in range(0, 64, 8)]
            before = [eng.predict(r) for r in rows]
            promoter = ShadowPromoter(eng, min_mirrored=1, fraction=1.0)
            promoter.stage("candidate", model_path=candidate_zip,
                           input_shape=(6,), max_batch=16)
            after = [eng.predict(r) for r in rows]
            for b, a in zip(before, after):
                np.testing.assert_array_equal(b, a)
            assert promoter.mirror.wait_idle()
            assert promoter.mirror.report()["mirrored"] == len(rows)
            promoter.abort("test teardown")
        finally:
            eng.stop(drain=False)

    def test_promotion_swap_zero_failed_requests(self, candidate_zip):
        """Contract (b): requests hammered across the atomic swap all
        succeed, and each answer is byte-attributable to exactly the
        primary or the candidate (never a torn mix)."""
        primary = serving_net()
        eng = ServingEngine(model=primary, input_shape=(6,), max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=2, fraction=1.0)
            rec = promoter.stage("candidate", model_path=candidate_zip,
                                 input_shape=(6,), max_batch=16)
            rows = X[:8]
            for _ in range(4):
                eng.predict(rows)
            assert promoter.mirror.wait_idle()
            want_primary = eng.predict(rows)
            cand_norm = rec.normalizer
            want_cand = np.asarray(
                rec.model.output(cand_norm.transform_array(rows)))

            stop = threading.Event()
            failures, answers = [], []

            def hammer():
                while not stop.is_set():
                    try:
                        answers.append(eng.predict(rows))
                    except Exception as e:  # noqa: BLE001 — the contract
                        failures.append(e)

            with ThreadPoolExecutor(4) as ex:
                futs = [ex.submit(hammer) for _ in range(4)]
                time.sleep(0.05)
                report = promoter.promote()
                time.sleep(0.05)
                stop.set()
                for f in futs:
                    f.result(timeout=30)

            assert report["ok"] and report["promoted"] == rec.key
            assert not failures, f"requests failed across swap: {failures!r}"
            assert answers
            for out in answers:
                assert (np.array_equal(out, want_primary)
                        or np.array_equal(out, want_cand)), "torn answer"
            # swap completed: the default now answers with the candidate
            np.testing.assert_array_equal(eng.predict(rows), want_cand)
            assert eng.registry.default().key == rec.key
            assert eng._shadow is None  # mirror detached after promotion
        finally:
            eng.stop(drain=False)

    def test_injected_warmup_failure_never_moves_default(self, candidate_zip):
        """Contract (b), failure half: chaos-injected warmup failure
        lands the candidate broken; the prior default keeps serving."""
        chaos = ServingChaos(ServingChaosConfig(warmup_fail_name="candidate"))
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16, chaos=chaos)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1)
            with pytest.raises(InjectedServingFault):
                promoter.stage("candidate", model_path=candidate_zip,
                               input_shape=(6,), max_batch=16)
            assert eng.registry.default().key == "default@v1"
            assert eng.registry.get("candidate").state == "broken"
            assert eng._shadow is None  # nothing attached on failed stage
            out = eng.predict(X[:8])    # prior default still answers
            assert out.shape == (8, 3)
        finally:
            eng.stop(drain=False)

    def test_gate_failure_refuses_and_breaks_candidate(self, candidate_zip):
        """A failed promotion gate (insufficient mirrored volume) refuses,
        marks the candidate broken, and never moves the default."""
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1000)
            rec = promoter.stage("candidate", model_path=candidate_zip,
                                 input_shape=(6,), max_batch=16)
            eng.predict(X[:8])
            with pytest.raises(PromotionRefused) as ei:
                promoter.promote()
            assert any("min_mirrored" in f for f in ei.value.report["failed"])
            assert eng.registry.default().key == "default@v1"
            assert eng.registry.get(rec.name, rec.version).state == "broken"
            assert promoter.online_stats.snapshot()["promotion_refusals"] == 1
        finally:
            eng.stop(drain=False)

    def test_shadow_errors_never_vote_primary_breaker(self, candidate_zip):
        """Satellite 3: a shadow model that CRASHES on every mirrored
        batch costs the client path nothing — no breaker vote, no failed
        request — and surfaces as a mirror_errors gate refusal."""
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1)
            rec = promoter.stage("candidate", model_path=candidate_zip,
                                 input_shape=(6,), max_batch=16)

            class Exploding:
                def output(self, x):
                    raise RuntimeError("shadow boom")

            rec.model = Exploding()  # sabotage AFTER warmup
            for _ in range(4):
                out = eng.predict(X[:8])  # client path never notices
                assert out.shape == (8, 3)
            assert promoter.mirror.wait_idle()
            snap = promoter.online_stats.snapshot()
            assert snap["mirror_errors"] >= 1
            assert eng.stats.snapshot()["breaker_opens"] == 0
            assert eng._breakers["default@v1"].state == "serving"
            with pytest.raises(PromotionRefused) as ei:
                promoter.promote()
            assert any("mirror_errors" in f
                       for f in ei.value.report["failed"])
            assert eng.registry.default().key == "default@v1"
        finally:
            eng.stop(drain=False)

    def test_drain_mid_shadow_seals_without_promoting(self, candidate_zip):
        """Satellite 3: a drain racing the promotion hits the SEALED
        registry — DrainingError, default unmoved, candidate NOT broken
        (a drain is not a verdict), mirror detached."""
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1, fraction=1.0)
            rec = promoter.stage("candidate", model_path=candidate_zip,
                                 input_shape=(6,), max_batch=16)
            eng.predict(X[:8])
            assert promoter.mirror.wait_idle()
            assert eng.drain(timeout_s=10.0)
            with pytest.raises(DrainingError):
                promoter.promote()
            assert eng.registry.default().key == "default@v1"
            assert eng.registry.get(rec.name, rec.version).state == "warm"
            assert eng._shadow is None
            # and a stage() after the drain began is refused outright
            with pytest.raises(DrainingError):
                promoter.stage("candidate2", model_path=candidate_zip,
                               input_shape=(6,), max_batch=16)
        finally:
            eng.stop(drain=False)

    def test_fraction_stride_deterministic(self, candidate_zip):
        """A 0.5 mirror fraction selects exactly every other answered
        request — accumulated stride, no RNG."""
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1, fraction=0.5)
            promoter.stage("candidate", model_path=candidate_zip,
                           input_shape=(6,), max_batch=16)
            for _ in range(8):
                eng.predict(X[:8])
            assert promoter.mirror.wait_idle()
            rep = promoter.mirror.report()
            assert rep["mirrored"] == 4 and rep["skipped"] == 4
            promoter.abort("test teardown")
        finally:
            eng.stop(drain=False)


# ---------------------------------------------------------------------------
# Contract (c): deterministic drift alarm blocks promotion
# ---------------------------------------------------------------------------


class TestDrift:
    def test_in_distribution_stays_quiet(self):
        mon = DriftMonitor(fitted_norm(), min_rows=32)
        for i in range(0, 96, 8):
            mon.observe(X[i:i + 8])
        v = mon.check()
        assert v["verdict"] == "ok" and not mon.alarmed
        assert v["max_z"] < 1.0  # the live window IS the fitted window

    def test_scripted_shift_alarms_deterministically(self):
        shifted = X + np.asarray([5, 0, 0, 0, 0, 0], np.float32)
        verdicts = []
        for _ in range(3):  # identical every run — pure arithmetic
            mon = DriftMonitor(fitted_norm(), min_rows=32, z_threshold=3.0)
            for i in range(0, 96, 8):
                mon.observe(shifted[i:i + 8])
            verdicts.append(mon.check())
        assert all(v["verdict"] == "alarm" for v in verdicts)
        assert len({round(v["max_z"], 9) for v in verdicts}) == 1
        assert verdicts[0]["column"] == 0  # the shifted column is named
        # pending below the minimum window: no verdict from thin evidence
        thin = DriftMonitor(fitted_norm(), min_rows=64)
        thin.observe(shifted[:8])
        assert thin.check()["verdict"] == "pending"

    def test_alarm_blocks_promotion(self, candidate_zip):
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            mon = DriftMonitor(fitted_norm(), min_rows=16, z_threshold=3.0)
            mon.observe(X[:32] + 50.0)  # scripted shift
            assert mon.check()["verdict"] == "alarm"
            promoter = ShadowPromoter(eng, drift=mon, min_mirrored=1)
            rec = promoter.stage("candidate", model_path=candidate_zip,
                                 input_shape=(6,), max_batch=16)
            eng.predict(X[:8])
            assert promoter.mirror.wait_idle()
            with pytest.raises(PromotionRefused) as ei:
                promoter.promote()
            assert "drift_alarm" in ei.value.report["failed"]
            assert eng.registry.default().key == "default@v1"
            assert eng.registry.get(rec.name, rec.version).state == "broken"
        finally:
            eng.stop(drain=False)

    def test_trainer_feeds_drift_window(self):
        """ContinuousTrainer offers every delivered batch to the monitor
        BEFORE the fit step — the drift window sees the training data."""
        mon = DriftMonitor(fitted_norm(), min_rows=16)
        src = StreamSource(watermark=64, idle_s=0.05)
        ct = ContinuousTrainer(build_net(), src, drift=mon,
                               workers=1, shard=None, handle_signals=False)
        push_all(src, upto=32)
        ct.fit_round()
        v = mon.check()
        assert v["rows"] == 32 and v["verdict"] == "ok"


# ---------------------------------------------------------------------------
# Satellite 2: version lineage
# ---------------------------------------------------------------------------


class TestLineage:
    def test_lineage_and_rollback_target(self, candidate_zip):
        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16)
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1, fraction=1.0)
            promoter.stage("candidate", model_path=candidate_zip,
                           input_shape=(6,), max_batch=16)
            eng.predict(X[:8])
            assert promoter.mirror.wait_idle()
            promoter.promote()
            reg = eng.registry
            assert reg.default().prior_default == "default@v1"
            lineage = reg.lineage()
            assert lineage[-1]["from"] == "default@v1"
            assert lineage[-1]["to"] == "candidate@v1"
            assert reg.rollback_target() == ("default", 1)
            # describe() carries the lineage pointer per record
            cand = [d for d in reg.describe() if d["name"] == "candidate"][0]
            assert cand["prior_default"] == "default@v1"
            # rollback re-serves the recorded prior and extends the chain
            promoter.rollback()
            assert reg.default().key == "default@v1"
            assert reg.lineage()[-1]["to"] == "default@v1"
        finally:
            eng.stop(drain=False)

    def test_models_endpoint_exposes_lineage(self, candidate_zip):
        import json
        import urllib.request

        eng = ServingEngine(model=serving_net(), input_shape=(6,),
                            max_batch=16).start()
        try:
            promoter = ShadowPromoter(eng, min_mirrored=1, fraction=1.0)
            promoter.stage("candidate", model_path=candidate_zip,
                           input_shape=(6,), max_batch=16)
            eng.predict(X[:8])
            assert promoter.mirror.wait_idle()
            promoter.promote()
            with urllib.request.urlopen(eng.url + "/models",
                                        timeout=30) as r:
                body = json.loads(r.read())
            assert body["default"] == "candidate@v1"
            assert body["lineage"][-1]["from"] == "default@v1"
            assert body["lineage"][-1]["to"] == "candidate@v1"
        finally:
            eng.stop(drain=False)


# ---------------------------------------------------------------------------
# Ledger plumbing
# ---------------------------------------------------------------------------


class TestOnlineStatsLedger:
    def test_trainer_ledger_registered_on_net(self):
        from deeplearning4j_tpu.obs.registry import default_registry

        src = StreamSource(watermark=8, idle_s=0.05)
        ct = ContinuousTrainer(build_net(), src, workers=1, shard=None,
                               handle_signals=False)
        assert ct.net.online_stats is ct.online_stats
        ledgers = default_registry().ledgers(ct.net)
        assert "online_stats" in ledgers
        push_all(src, upto=16)
        ct.fit_round()
        snap = ct.snapshot()
        assert snap["rounds"] == 1
        assert snap["delivered_batches"] == 2
        assert snap["pushed_batches"] == 2
