"""OS-process fleet member for the elastic-fleet tests (test_fleet.py).

One worker of the cross-process fleet: control plane over the
coordinator's StateTrackerServer TCP transport (RemoteStateTracker),
data plane over the spool directory (split / round-state / result npz
files) — the reference's worker JVM role (ExecuteWorkerFlatMap over the
Hazelcast member plane). SIGTERM makes it checkpoint nothing and
announce departure (the coordinator owns the authoritative checkpoint);
the parent test asserts the fleet rebalances and the run stays bit-exact.

Usage: fleet_worker.py <host:port> <worker_id> <spool_dir> [idle_exit_s]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # match the pytest parent env

from deeplearning4j_tpu.parallel.fleet import run_worker  # noqa: E402


def main() -> None:
    address, worker_id, spool = sys.argv[1], sys.argv[2], sys.argv[3]
    idle = float(sys.argv[4]) if len(sys.argv) > 4 else None
    print(f"FLEET_WORKER_UP {worker_id}", flush=True)
    run_worker(address, worker_id, spool, stop_after_idle_s=idle)
    print(f"FLEET_WORKER_DONE {worker_id}", flush=True)


if __name__ == "__main__":
    main()
