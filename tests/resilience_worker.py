"""Subprocess driver for the preemption tests (tests/test_resilience.py).

Runs a deterministic MLP fit under ResilientTrainer exactly as a user
process would, in three modes:

  baseline  — plain uninterrupted fit (no manager, no chaos)
  train     — managed fit; with RES_KILL_STEP set, chaos delivers a REAL
              SIGTERM to this process after that step -> the trainer's
              checkpoint-before-death path commits a goodbye checkpoint
              and the process exits 143 (after dumping its loss curve so
              the parent can stitch). Re-exec'd with the same checkpoint
              dir and no kill, it resumes and finishes.

Every mode dumps final params + losses + the resume step to an npz the
parent compares bit-for-bit.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.resilience import (  # noqa: E402
    ChaosConfig,
    ChaosMonkey,
    CheckpointManager,
    Preempted,
    ResilientTrainer,
)

EPOCHS = 2


def build() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def make_iterator() -> ListDataSetIterator:
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    return ListDataSetIterator(x, y, batch=8)


def dump(path: str, trainer: ResilientTrainer) -> None:
    leaves = jax.tree_util.tree_leaves(trainer.net.params)
    np.savez(
        path,
        losses=np.asarray(trainer.losses, np.float64),
        resumed=np.asarray(
            -1 if trainer.resumed_step is None else trainer.resumed_step),
        step=np.asarray(trainer.step),
        **{f"p{i}": np.asarray(a) for i, a in enumerate(leaves)},
    )


def main() -> None:
    mode, ckpt_dir, out = sys.argv[1], sys.argv[2], sys.argv[3]
    it = make_iterator()
    if mode == "baseline":
        trainer = ResilientTrainer(build())
        trainer.fit(it, num_epochs=EPOCHS)
    elif mode == "train":
        manager = CheckpointManager(ckpt_dir, every_steps=3, keep_last=3)
        kill = int(os.environ.get("RES_KILL_STEP", "0"))
        chaos = (ChaosMonkey(ChaosConfig(kill_at_step=kill,
                                         kill_mode="sigterm"))
                 if kill else None)
        trainer = ResilientTrainer(build(), manager, chaos=chaos)
        try:
            trainer.fit(it, num_epochs=EPOCHS)
        except Preempted as e:
            dump(out, trainer)
            print(f"PREEMPTED step={e.step} ckpt={e.path}")
            sys.exit(143)
        finally:
            manager.close()
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    dump(out, trainer)
    print(f"DONE step={trainer.step} resumed={trainer.resumed_step}")


if __name__ == "__main__":
    main()
