"""Distributed==serial equivalence suite on the virtual 8-device CPU mesh.

Mirrors the reference's key distributed test idea
(TestCompareParameterAveragingSparkVsSingleMachine.java:115-262, SURVEY.md
section 4): N-worker training must equal the serial equivalent exactly.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.fetchers import load_iris
from deeplearning4j_tpu.nn.conf import DenseLayer, NeuralNetConfiguration, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelWrapper, ParameterAveragingTrainer
from deeplearning4j_tpu.parallel.mesh import device_mesh


def iris_net(seed=42, lr=0.1, updater="sgd"):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater(updater)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def assert_params_close(p1, p2, rtol=1e-6, atol=1e-7):
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def test_mesh_has_8_devices():
    mesh = device_mesh()
    assert int(np.prod(mesh.devices.shape)) == 8


def test_dp_equals_single_device():
    """Gradient DP over 8 shards == single-device large batch (same XLA
    program, sharded) — the strong equivalence our DP mode guarantees."""
    x, y = load_iris()
    x, y = x[:144], y[:144]
    serial = iris_net(seed=5)
    parallel_net = iris_net(seed=5)
    pw = ParallelWrapper(parallel_net, num_devices=8)
    for _ in range(5):
        serial.fit(x, y)
        pw.fit(x, y)
    assert_params_close(serial.params, parallel_net.params, rtol=1e-5, atol=1e-6)


def test_dp_batch_not_divisible_raises():
    net = iris_net()
    pw = ParallelWrapper(net, num_devices=8)
    x, y = load_iris()
    with pytest.raises(ValueError):
        pw.fit(x[:100], y[:100])


def test_param_averaging_freq1_sgd_equals_big_batch():
    """averagingFrequency=1 + plain SGD: averaging N independent one-step
    params == one step on the concatenated batch (gradient linearity) —
    the reference equivalence assertion (:115-262)."""
    x, y = load_iris()
    x, y = x[:144], y[:144]

    avg_net = iris_net(seed=11)
    trainer = ParameterAveragingTrainer(
        avg_net, num_workers=8, averaging_frequency=1
    )
    trainer.fit(x, y)

    serial = iris_net(seed=11)
    serial.fit(x, y)

    assert_params_close(serial.params, avg_net.params, rtol=1e-5, atol=1e-6)


def test_param_averaging_multi_round_trains():
    x, y = load_iris()
    x, y = x[:144], y[:144]
    net = iris_net(seed=13, updater="adam", lr=0.05)
    trainer = ParameterAveragingTrainer(net, num_workers=8, averaging_frequency=3)
    s0 = net.score(x, y)
    for _ in range(20):
        trainer.fit(x, y)
    s1 = net.score(x, y)
    assert s1 < s0 * 0.8, f"{s0} -> {s1}"


def test_param_averaging_differs_from_grad_sync_when_freq_gt1():
    """freq>1 local steps diverge from lockstep DP — guards that the two
    modes really implement different semantics."""
    x, y = load_iris()
    x, y = x[:128], y[:128]
    a = iris_net(seed=17)
    b = iris_net(seed=17)
    ParameterAveragingTrainer(a, num_workers=8, averaging_frequency=4).fit(x, y)
    pw = ParallelWrapper(b, num_devices=8)
    for i in range(4):
        pw.fit(x[i * 32 : (i + 1) * 32], y[i * 32 : (i + 1) * 32])
    diffs = [
        float(np.max(np.abs(np.asarray(p) - np.asarray(q))))
        for p, q in zip(
            jax.tree_util.tree_leaves(a.params), jax.tree_util.tree_leaves(b.params)
        )
    ]
    assert max(diffs) > 1e-6


def test_graft_entry_dryrun():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 1000)  # ResNet-50 flagship
    ge.dryrun_multichip(8)


# ----------------------------------------------------------------- DP TBPTT
def char_lstm_net(seed=3, fwd=4, back=4):
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.1)
        .weight_init("xavier")
        .list()
        .layer(0, GravesLSTM(n_in=5, n_out=6, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=6, n_out=5, activation="softmax",
                                 loss_function="mcxent"))
        .backprop_type("truncated_bptt")
        .t_bptt_forward_length(fwd)
        .t_bptt_backward_length(back)
        .build()
    )
    return MultiLayerNetwork(conf).init(input_shape=(1, 5))


def _seq_data(n=16, t=8, f=5, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, f, (n, t + 1))
    eye = np.eye(f, dtype=np.float32)
    return eye[ids[:, :t]], eye[ids[:, 1:]]


def test_dp_tbptt_equals_serial():
    """DP truncated-BPTT window loop == serial TBPTT (char-RNN config trains
    data-parallel; VERDICT round-1 weak #5)."""
    x, y = _seq_data()
    serial = char_lstm_net(seed=3)
    parallel_net = char_lstm_net(seed=3)
    pw = ParallelWrapper(parallel_net, num_devices=8)
    for _ in range(3):
        serial.fit(x, y)
        pw.fit(x, y)
    assert serial.iteration == parallel_net.iteration
    assert_params_close(serial.params, parallel_net.params, rtol=2e-5, atol=1e-6)


def test_dp_tbptt_distinct_back_length_trains():
    x, y = _seq_data()
    serial = char_lstm_net(seed=4, fwd=4, back=2)
    parallel_net = char_lstm_net(seed=4, fwd=4, back=2)
    pw = ParallelWrapper(parallel_net, num_devices=8)
    serial.fit(x, y)
    pw.fit(x, y)
    assert_params_close(serial.params, parallel_net.params, rtol=2e-5, atol=1e-6)


def test_param_averaging_masked_sequences():
    """ParameterAveragingTrainer threads feature/label masks through the
    shard_map workers (VERDICT round-1 weak #6) and leaves recurrent stream
    state un-averaged."""
    x, y = _seq_data(n=32, t=6)
    mask = np.ones((32, 6), np.float32)
    mask[:, 4:] = 0.0  # all sequences effectively length 4

    net_m = char_lstm_net(seed=9, fwd=6, back=6)
    net_u = char_lstm_net(seed=9, fwd=6, back=6)
    # standard backprop for this test: PA trainer works on whole sequences
    net_m.conf.backprop_type = net_u.conf.backprop_type = "standard"

    pa_m = ParameterAveragingTrainer(net_m, num_workers=8, averaging_frequency=2)
    pa_u = ParameterAveragingTrainer(net_u, num_workers=8, averaging_frequency=2)
    for _ in range(2):
        loss_m = pa_m.fit(x, y, mask=mask, label_mask=mask)
        loss_u = pa_u.fit(x, y)
    assert np.isfinite(float(loss_m)) and np.isfinite(float(loss_u))
    # masking the tail must change the learned params
    w_m = np.asarray(net_m.params[0]["W"])
    w_u = np.asarray(net_u.params[0]["W"])
    assert not np.allclose(w_m, w_u)


def test_parallel_fit_batches_equals_serial():
    """Fused K-step DP scan == serial single-device fit_batches (GSPMD DP
    is numerically big-batch training)."""
    from deeplearning4j_tpu.datasets.fetchers import load_iris

    x, y = load_iris()
    K, N = 2, 48
    xs = np.stack([x[i * N:(i + 1) * N] for i in range(K)])
    ys = np.stack([y[i * N:(i + 1) * N] for i in range(K)])

    serial = iris_net(seed=31)
    serial_losses = serial.fit_batches(xs, ys)
    dp_net = iris_net(seed=31)
    pw = ParallelWrapper(dp_net, num_devices=8)
    dp_losses = pw.fit_batches(xs, ys)
    np.testing.assert_allclose(dp_losses, serial_losses, rtol=1e-5)
    for p_s, p_f in zip(serial.params, dp_net.params):
        for name in p_s:
            np.testing.assert_allclose(
                np.asarray(p_f[name]), np.asarray(p_s[name]),
                rtol=1e-5, atol=1e-6, err_msg=name,
            )
