"""Sequence-parallel TRAINING == serial training.

Ring attention previously stopped at forward/eval; make_ring_train_step
composes it with loss + Adam so long-context sequences take real optimizer
steps. Distributed==serial convention: same batches, same seed, matching
loss curves and end-state params (the reference's closest analog is
TestCompareParameterAveragingSparkVsSingleMachine; the ring axis itself is
beyond the reference — SURVEY.md section 2.7 / section 5 long-context).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    init_opt_state,
    init_params,
    make_ring_train_step,
    make_train_step,
)


def _cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 2)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("learning_rate", 1e-3)
    kw.setdefault("use_flash", False)
    return TransformerConfig(**kw)


def _batches(cfg, n=4, k=4, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (k, n, cfg.max_len + 1))
    return (jnp.asarray(toks[:, :, :-1], jnp.int32),
            jnp.asarray(toks[:, :, 1:], jnp.int32))


def _run_curve(step, params, opt, xs, ys):
    losses = []
    for i in range(xs.shape[0]):
        params, opt, loss = step(params, opt, xs[i], ys[i])
        losses.append(float(loss))
    return params, losses


class TestRingTrainStep:
    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_sp_train_matches_serial_curve(self, strategy):
        cfg = _cfg()
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        p_s, curve_s = _run_curve(serial, params, init_opt_state(params),
                                  xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        sp = make_ring_train_step(cfg, mesh, strategy=strategy)
        p_p, curve_p = _run_curve(sp, params, init_opt_state(params), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4,
                                   err_msg=f"{strategy} curve != serial")
        np.testing.assert_allclose(
            np.asarray(p_p["blocks"]["Wq"]), np.asarray(p_s["blocks"]["Wq"]),
            atol=1e-5)

    def test_sp_moe_train_matches_serial_curve(self):
        """SP x MoE (round-4: the former 'dense FFN only' rejection):
        ring_forward(return_aux=True) threads the load-balance aux loss
        through, so the SP step optimizes the identical objective —
        curves and end-state expert weights must match serial."""
        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        p_s, curve_s = _run_curve(serial, params, init_opt_state(params),
                                  xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        sp = make_ring_train_step(cfg, mesh)
        p_p, curve_p = _run_curve(sp, params, init_opt_state(params), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4,
                                   err_msg="SP MoE curve != serial")
        np.testing.assert_allclose(
            np.asarray(p_p["blocks"]["W1"]), np.asarray(p_s["blocks"]["W1"]),
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(p_p["blocks"]["Wg"]), np.asarray(p_s["blocks"]["Wg"]),
            atol=1e-5)

    def test_dpxsp_train_matches_serial_curve(self):
        cfg = _cfg()
        xs, ys = _batches(cfg)
        params = init_params(cfg)
        serial = make_train_step(cfg)
        _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                xs, ys)

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "seq"))
        sp = make_ring_train_step(cfg, mesh)
        _, curve_p = _run_curve(sp, params, init_opt_state(params), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4)

    def test_multi_step_factory_validates_too(self):
        """Guards live in the shared builder: the multi-step factory must
        reject the same configs as the single-step one. (MoE is no longer
        rejected — test_sp_moe_train_matches_serial_curve covers it.)"""
        from deeplearning4j_tpu.models.transformer import (
            make_ring_train_multi_step,
        )

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        with pytest.raises(ValueError):
            make_ring_train_multi_step(_cfg(accum_steps=2), mesh)

    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_bf16_policy_trains_close_to_serial(self, strategy):
        """dtype_policy='performance' runs the block body in bf16 (half the
        ppermute bytes on real ICI); rounding differs from the serial bf16
        scan path, so the bar is tolerance, not bit equality. Both
        strategies covered — Ulysses' softmax must upcast to f32 even with
        bf16 q/k/v (multi_head_attention)."""
        cfg = _cfg(dtype_policy="performance", learning_rate=1e-2)
        xs, ys = _batches(cfg, k=5)
        serial = make_train_step(cfg)
        params = init_params(cfg)
        _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        sp = make_ring_train_step(cfg, mesh, strategy=strategy)
        _, curve_p = _run_curve(sp, params, init_opt_state(params), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=5e-2)
        assert all(np.isfinite(curve_p))


class TestTransformerLMSequenceMode:
    def test_lm_on_seq_mesh_trains_and_matches_serial(self):
        cfg = _cfg()
        xs, ys = _batches(cfg, k=3)
        serial = TransformerLM(cfg)
        curve_s = [float(serial.fit(xs[i], ys[i])) for i in range(3)]

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        lm = TransformerLM(cfg, mesh=mesh)
        curve_p = [float(lm.fit(xs[i], ys[i])) for i in range(3)]
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4)
        assert lm.iteration == 3

    def test_lm_seq_fit_batches_fused(self):
        cfg = _cfg()
        xs, ys = _batches(cfg, k=3)
        serial = TransformerLM(cfg)
        curve_s = [float(serial.fit(xs[i], ys[i])) for i in range(3)]

        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        lm = TransformerLM(cfg, mesh=mesh)
        losses = lm.fit_batches(xs, ys)
        np.testing.assert_allclose(np.asarray(losses), curve_s, rtol=1e-4)
