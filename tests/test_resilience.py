"""Fault-tolerant training runtime (deeplearning4j_tpu/resilience/).

The headline contract is the resilience analogue of the repo's
distributed==serial convention: training KILLED at step k (via the
deterministic chaos harness) and RESUMED from the async checkpoint
produces bit-identical final params and loss curve to the uninterrupted
run — for MultiLayerNetwork, ComputationGraph, and the DP
ParameterAveragingTrainer, including RNG and data-iterator cursor state.
Plus: corruption detection with fallback (truncation/bit-flip), retention
policy, SIGTERM preemption -> checkpoint-before-death -> re-exec resume
(real subprocesses), transient-error retry, and the zero-behavior-change
contract for a disabled harness.
"""

import os
import signal
import subprocess
import sys
import threading
import time
import zipfile

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import (
    ChaosConfig,
    ChaosMonkey,
    CheckpointCorrupt,
    CheckpointManager,
    InjectedKill,
    ResilientTrainer,
    TransientDeviceError,
)
from deeplearning4j_tpu.resilience import chaos as chaos_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# deterministic shared data (f32: the equivalence bar is bit-identity)
_RNG = np.random.default_rng(0)
X = _RNG.standard_normal((48, 6)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[_RNG.integers(0, 3, 48)]


def build_mln() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
        .updater("adam").list()
        .layer(0, DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def build_cg() -> ComputationGraph:
    conf = (
        NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
        .updater("adam").graph_builder().add_inputs("in")
        .add_layer("d", DenseLayer(n_in=6, n_out=8, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out").build()
    )
    return ComputationGraph(conf)


def mk_iterator(batch: int = 8) -> ListDataSetIterator:
    return ListDataSetIterator(X, Y, batch=batch)


def params_equal(a, b) -> bool:
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb)
    )


def _resume_equivalence(builder, kill_at: int, tmp: str,
                        epochs: int = 3) -> None:
    """Kill at step k, restore from the async checkpoint, finish: final
    params AND loss curve bit-identical to the uninterrupted run."""
    baseline = ResilientTrainer(builder())
    baseline.fit(mk_iterator(), num_epochs=epochs)

    mgr = CheckpointManager(tmp, every_steps=4, keep_last=3)
    killed = ResilientTrainer(
        builder(), mgr, chaos=ChaosMonkey(ChaosConfig(kill_at_step=kill_at)))
    with pytest.raises(InjectedKill):
        killed.fit(mk_iterator(), num_epochs=epochs)
    mgr.close()

    mgr2 = CheckpointManager(tmp, every_steps=4, keep_last=3)
    resumed = ResilientTrainer(builder(), mgr2)
    resumed.fit(mk_iterator(), num_epochs=epochs)
    mgr2.close()

    assert resumed.resumed_step is not None
    assert 0 < resumed.resumed_step <= kill_at
    assert resumed.step == baseline.step
    stitched = killed.losses[:resumed.resumed_step] + resumed.losses
    assert stitched == baseline.losses, "loss curve diverged after resume"
    assert params_equal(baseline.net.params, resumed.net.params)
    assert params_equal(baseline.net.updater_state,
                        resumed.net.updater_state)


def test_resume_equivalence_mln(tmp_path):
    _resume_equivalence(build_mln, kill_at=10, tmp=str(tmp_path))


def test_resume_equivalence_cg(tmp_path):
    _resume_equivalence(build_cg, kill_at=10, tmp=str(tmp_path))


def test_resume_equivalence_param_averaging(tmp_path):
    """The DP trainer (ParameterAveragingTrainer, shard_map workers on the
    virtual mesh): killed mid-run, restored, == uninterrupted bit-exact.
    One iterator batch = one averaging round."""
    from deeplearning4j_tpu.parallel.data_parallel import (
        ParameterAveragingTrainer,
    )

    n_workers, freq = 4, 1
    it = lambda: ListDataSetIterator(X, Y, batch=16)  # 16 = freq*4 workers*4

    def run(manager=None, chaos=None):
        trainer = ResilientTrainer(
            ParameterAveragingTrainer(build_mln(), num_workers=n_workers,
                                      averaging_frequency=freq),
            manager, chaos=chaos)
        return trainer

    baseline = run()
    baseline.fit(it(), num_epochs=2)

    mgr = CheckpointManager(str(tmp_path), every_steps=2, keep_last=2)
    killed = run(mgr, ChaosMonkey(ChaosConfig(kill_at_step=4)))
    with pytest.raises(InjectedKill):
        killed.fit(it(), num_epochs=2)
    mgr.close()

    mgr2 = CheckpointManager(str(tmp_path), every_steps=2, keep_last=2)
    resumed = run(mgr2)
    resumed.fit(it(), num_epochs=2)
    mgr2.close()

    assert resumed.resumed_step == 4
    stitched = killed.losses[:4] + resumed.losses
    assert stitched == baseline.losses
    assert params_equal(baseline.net.params, resumed.net.params)
    assert baseline.net.iteration == resumed.net.iteration


# ---------------------------------------------------------------- manager
def test_async_checkpoint_matches_sync(tmp_path):
    """The async writer must commit the state AS OF the save call, not as
    of write time: train 3 steps, save async, train 3 more, flush — the
    checkpoint equals a sync save taken at the same step."""
    net = build_mln().init()
    for i in range(3):
        net.fit(X[:8], Y[:8])
    sync_mgr = CheckpointManager(str(tmp_path / "sync"), async_save=False)
    sync_mgr.save(net, step=3)
    async_mgr = CheckpointManager(str(tmp_path / "async"), async_save=True)
    async_mgr.save(net, step=3)
    for i in range(3):  # keep training while the async write is in flight
        net.fit(X[:8], Y[:8])
    async_mgr.flush()
    async_mgr.close()

    a, b = build_mln(), build_mln()
    s1 = sync_mgr.restore_latest(a)
    s2 = async_mgr.restore_latest(b)
    assert s1["step"] == s2["step"] == 3
    assert params_equal(a.params, b.params)
    assert params_equal(a.updater_state, b.updater_state)
    assert a.iteration == b.iteration == 3


def test_retention_keep_last_and_every(tmp_path):
    mgr = CheckpointManager(str(tmp_path), every_steps=1, keep_last=2,
                            keep_every=4, async_save=False)
    net = build_mln().init()
    for step in range(1, 10):
        mgr.save(net, step=step)
    steps = [s for s, _ in mgr.checkpoints()]
    assert steps == [4, 8, 9]  # keep_every anchors {4,8} + last 2 {8,9}
    assert mgr.stats["pruned"] > 0


def test_corrupt_bitflip_falls_back(tmp_path, caplog):
    mgr = CheckpointManager(str(tmp_path), async_save=False, keep_last=5)
    net = build_mln().init()
    net.fit(X[:8], Y[:8])
    mgr.save(net, step=1)
    net.fit(X[:8], Y[:8])
    mgr.save(net, step=2)
    (_, newest), = [c for c in mgr.checkpoints() if c[0] == 2]
    chaos_mod.bitflip_file(os.path.join(newest, "model.zip"))
    fresh = build_mln()
    import logging

    with caplog.at_level(logging.WARNING, logger="deeplearning4j_tpu"):
        restored = mgr.restore_latest(fresh)
    assert restored is not None and restored["step"] == 1
    assert any("corrupt" in r.message for r in caplog.records)
    assert fresh.iteration == 1


def test_corrupt_truncate_all_is_loud(tmp_path):
    """Every retained checkpoint truncated: restore_latest returns None
    (fresh start) and an explicit restore raises — never silent garbage."""
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    net = build_mln().init()
    mgr.save(net, step=1)
    (_, path), = mgr.checkpoints()
    chaos_mod.truncate_file(os.path.join(path, "model.zip"), keep=10)
    assert mgr.restore_latest(build_mln()) is None
    with pytest.raises(CheckpointCorrupt):
        mgr.restore(path, build_mln())


def test_chaos_driven_corruption_via_manager(tmp_path):
    """The write-then-truncate fault wired through the manager's chaos
    hook (config-driven, as the tests are meant to use it)."""
    chaos = ChaosMonkey(ChaosConfig(
        corrupt_checkpoint={"at_step": 2, "mode": "truncate"}))
    mgr = CheckpointManager(str(tmp_path), async_save=False, keep_last=5,
                            chaos=chaos)
    net = build_mln().init()
    net.fit(X[:8], Y[:8])
    mgr.save(net, step=1)
    net.fit(X[:8], Y[:8])
    mgr.save(net, step=2)
    assert (2, "corrupt:truncate") in chaos.log
    found = mgr.latest_intact()
    assert found is not None
    assert found[1]["step"] == 1  # fell back past the truncated step-2


def test_skip_when_writer_busy(tmp_path, monkeypatch):
    """Non-blocking saves never queue without bound: while a write is in
    flight, further cadence saves are skipped and counted."""
    mgr = CheckpointManager(str(tmp_path), async_save=True, keep_last=9)
    slow = {"done": False}
    orig = mgr._write_zip_payload

    def slow_payload(tmp, job):
        time.sleep(0.4)
        return orig(tmp, job)

    monkeypatch.setattr(mgr, "_write_zip_payload", slow_payload)
    net = build_mln().init()
    for step in range(1, 8):
        mgr.save(net, step=step)
    mgr.flush()
    mgr.close()
    assert mgr.stats["skipped_busy"] > 0
    assert mgr.stats["saves"] >= 1
    assert mgr.stats["saves"] + mgr.stats["skipped_busy"] == 7


def test_manager_reuse_after_close_does_not_deadlock(tmp_path):
    """Regression: the close() sentinel must be task_done'd — a manager
    reused after close() (worker restarts on the next async save) would
    otherwise hang every later flush() in queue.join()."""
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    net = build_mln().init()
    mgr.save(net, step=1)
    mgr.flush()
    mgr.close()
    mgr.save(net, step=2)
    done = threading.Event()

    def flusher():
        mgr.flush()
        done.set()

    t = threading.Thread(target=flusher, daemon=True)
    t.start()
    assert done.wait(timeout=30.0), "flush() deadlocked after close+reuse"
    mgr.close()
    assert [s for s, _ in mgr.checkpoints()] == [1, 2]


def test_blocking_save_error_not_rereported_by_flush(tmp_path, monkeypatch):
    """Regression: an error RAISED by a blocking save is handled by the
    caller; flush() must not re-raise it later."""
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    net = build_mln().init()

    def boom(tmp, job):
        raise OSError("disk full (injected)")

    monkeypatch.setattr(mgr, "_write_zip_payload", boom)
    with pytest.raises(OSError, match="disk full"):
        mgr.save(net, step=1)
    monkeypatch.undo()
    mgr.save(net, step=2)
    mgr.flush()  # must NOT re-raise the step-1 error
    assert [s for s, _ in mgr.checkpoints()] == [2]


def test_non_primary_process_never_writes(tmp_path):
    mgr = CheckpointManager(str(tmp_path), primary=False, async_save=False)
    mgr.save(build_mln().init(), step=1)
    assert mgr.checkpoints() == []


def test_sharded_backend_roundtrip(tmp_path):
    """The orbax layering: same manifest/verify/restore plane over the
    sharded layout (utils/sharded_checkpoint.py)."""
    pytest.importorskip("orbax.checkpoint")
    net = build_mln().init()
    net.fit(X[:8], Y[:8])
    mgr = CheckpointManager(str(tmp_path), backend="sharded",
                            async_save=False)
    mgr.save(net, step=1)
    path, manifest = mgr.latest_intact()
    assert manifest["backend"] == "sharded"
    fresh = build_mln().init()
    restored = mgr.restore(path, fresh)
    assert restored["step"] == 1
    assert params_equal(net.params, fresh.params)
    assert fresh.iteration == net.iteration


# ----------------------------------------------------------------- chaos
def test_transient_error_retry_with_backoff(tmp_path):
    """A transient device error at step k, retried with backoff, leaves
    the run bit-identical to the uninterrupted one (the step eventually
    ran exactly once)."""
    baseline = ResilientTrainer(build_mln())
    baseline.fit(mk_iterator(), num_epochs=1)

    chaos = ChaosMonkey(ChaosConfig(transient_error_at_step=3,
                                    transient_error_count=2))
    retried = ResilientTrainer(build_mln(), chaos=chaos,
                               max_step_retries=2, retry_backoff_s=0.01)
    retried.fit(mk_iterator(), num_epochs=1)
    assert [s for s, f in chaos.log if f == "transient_error"] == [3, 3]
    assert retried.losses == baseline.losses
    assert params_equal(baseline.net.params, retried.net.params)


def test_retry_backoff_capped_and_jittered():
    """Satellite (ISSUE 6): exponential backoff saturates at
    retry_backoff_max_s, carries a bounded deterministic jitter, and the
    retries/backoff-seconds land in resilience_stats."""
    trainer = ResilientTrainer(build_mln(), max_step_retries=8,
                               retry_backoff_s=0.5, retry_backoff_max_s=2.0,
                               retry_jitter=0.25)
    vals = [trainer._retry_backoff(a) for a in range(1, 9)]
    for a, v in enumerate(vals, start=1):
        base = min(2.0, 0.5 * 2 ** (a - 1))
        assert base <= v <= base * 1.25, (a, v)
    assert max(vals) <= 2.0 * 1.25  # the cap holds at high attempt counts
    # deterministic: same (step, attempt) -> same jitter, different
    # attempts -> decorrelated sleeps (the thundering-herd fix)
    assert vals == [trainer._retry_backoff(a) for a in range(1, 9)]
    assert len({round(v / min(2.0, 0.5 * 2 ** (a - 1)), 6)
                for a, v in enumerate(vals, start=1)}) > 1


def test_resilience_stats_counts_retries_and_rides_listener_chain():
    """resilience_stats sits on the net beside dispatch_stats, counts
    retries + accumulated backoff, and ResilienceStatsListener surfaces
    it through the listener chain."""
    from deeplearning4j_tpu.optimize.listeners import ResilienceStatsListener

    chaos = ChaosMonkey(ChaosConfig(transient_error_at_step=3,
                                    transient_error_count=2))
    trainer = ResilientTrainer(build_mln(), chaos=chaos,
                               max_step_retries=2, retry_backoff_s=0.01)
    listener = ResilienceStatsListener(frequency=1)
    trainer.net.set_listeners(listener)
    trainer.fit(mk_iterator(), num_epochs=1)
    stats = trainer.net.resilience_stats
    assert stats is trainer.resilience_stats
    assert stats["retries"] == 2
    assert stats["backoff_seconds"] > 0
    assert listener.snapshots, "listener never saw resilience_stats"
    assert listener.snapshots[-1]["retries"] == 2


def test_transient_error_exhausts_retries():
    chaos = ChaosMonkey(ChaosConfig(transient_error_at_step=2,
                                    transient_error_count=5))
    trainer = ResilientTrainer(build_mln(), chaos=chaos,
                               max_step_retries=1, retry_backoff_s=0.0)
    with pytest.raises(TransientDeviceError):
        trainer.fit(mk_iterator(), num_epochs=1)


def test_stalled_feed_only_delays():
    chaos = ChaosMonkey(ChaosConfig(stall_at_step=2, stall_seconds=0.2))
    baseline = ResilientTrainer(build_mln())
    baseline.fit(mk_iterator(), num_epochs=1)
    stalled = ResilientTrainer(build_mln(), chaos=chaos)
    t0 = time.perf_counter()
    stalled.fit(mk_iterator(), num_epochs=1)
    assert time.perf_counter() - t0 >= 0.2
    assert stalled.losses == baseline.losses
    assert params_equal(baseline.net.params, stalled.net.params)


def test_disabled_harness_is_zero_change():
    """Chaos faults are opt-in: a ResilientTrainer with no manager and no
    chaos is bit-identical to the plain fit loop."""
    plain = build_mln()
    for epoch in range(2):
        for ds in mk_iterator():
            plain.fit(ds.features, ds.labels)
    wrapped = ResilientTrainer(build_mln())
    wrapped.fit(mk_iterator(), num_epochs=2)
    assert params_equal(plain.params, wrapped.net.params)
    assert plain.iteration == wrapped.net.iteration


# -------------------------------------------------------------- iterators
def test_list_iterator_state_roundtrip():
    it = mk_iterator(batch=8)
    seen = []
    for i, ds in enumerate(it):
        seen.append(ds)
        if i == 2:
            st = it.state()
            break
    assert st == {"cursor": 3}
    it2 = mk_iterator(batch=8)
    it2.restore_state(st)
    rest = list(it2)
    assert len(seen) + len(rest) == 6
    full = list(mk_iterator(batch=8))
    for got, want in zip(seen + rest, full):
        assert np.array_equal(got.features, want.features)
    # normal passes are unaffected after the one-shot resume
    assert len(list(it2)) == 6


def test_sampling_iterator_state_roundtrip():
    mk = lambda: SamplingDataSetIterator(X, Y, batch=4, total_batches=6,
                                         seed=3)
    full = [ds.features for ds in mk()]
    it = mk()
    out = []
    for i, ds in enumerate(it):
        out.append(ds.features)
        if i == 1:
            st = it.state()
            break
    it2 = SamplingDataSetIterator(X, Y, batch=4, total_batches=6, seed=999)
    it2.restore_state(st)  # rng_state overrides the wrong seed
    out += [ds.features for ds in it2]
    assert len(out) == 6
    for got, want in zip(out, full):
        assert np.array_equal(got, want)


def test_multiple_epochs_iterator_state_roundtrip():
    mk = lambda: MultipleEpochsIterator(3, mk_iterator(batch=16))
    full = [ds.features for ds in mk()]
    it = mk()
    out = []
    for i, ds in enumerate(it):
        out.append(ds.features)
        if i == 4:  # mid-second-epoch (3 batches/epoch)
            st = it.state()
            break
    assert st["epoch"] == 1
    it2 = mk()
    it2.restore_state(st)
    out += [ds.features for ds in it2]
    assert len(out) == len(full) == 9
    for got, want in zip(out, full):
        assert np.array_equal(got, want)


def test_async_iterator_state_is_delivered_not_prefetched():
    """The async wrapper's cursor counts batches DELIVERED to the
    consumer, not batches its producer prefetched — resuming from its
    state() replays exactly the undelivered remainder."""
    base = mk_iterator(batch=8)
    it = AsyncDataSetIterator(base, queue_size=4, device_put=False)
    got = []
    for i, ds in enumerate(it):
        if i == 1:
            time.sleep(0.1)  # let the producer run ahead
            st = it.state()
        got.append(ds.features)
        if i == 2:
            break
    assert st == {"cursor": 2}
    res = AsyncDataSetIterator(mk_iterator(batch=8), device_put=False)
    res.restore_state(st)
    rest = [np.asarray(ds.features) for ds in res]
    full = [ds.features for ds in mk_iterator(batch=8)]
    assert len(rest) == 4
    for got_f, want in zip(rest, full[2:]):
        assert np.array_equal(got_f, want)


def test_trainer_resume_through_async_iterator(tmp_path):
    """End-to-end: the prefetching iterator wrapped around the resumable
    base still yields an exact resume."""
    mk = lambda: AsyncDataSetIterator(mk_iterator(batch=8), queue_size=2,
                                      device_put=False)
    baseline = ResilientTrainer(build_mln())
    baseline.fit(mk(), num_epochs=2)
    mgr = CheckpointManager(str(tmp_path), every_steps=3, keep_last=3)
    killed = ResilientTrainer(build_mln(), mgr,
                              chaos=ChaosMonkey(ChaosConfig(kill_at_step=7)))
    with pytest.raises(InjectedKill):
        killed.fit(mk(), num_epochs=2)
    mgr.close()
    mgr2 = CheckpointManager(str(tmp_path), every_steps=3, keep_last=3)
    resumed = ResilientTrainer(build_mln(), mgr2)
    resumed.fit(mk(), num_epochs=2)
    mgr2.close()
    stitched = killed.losses[:resumed.resumed_step] + resumed.losses
    assert stitched == baseline.losses
    assert params_equal(baseline.net.params, resumed.net.params)


# ----------------------------------------------------------- serialization
def test_zip_training_state_section_roundtrip(tmp_path):
    """Satellite: the optional training-state section in the checkpoint
    zip (updater step, RNG key, epoch/cursor) — and old 3-part zips stay
    loadable."""
    from deeplearning4j_tpu.utils.serialization import (
        ModelSerializer,
        read_training_state,
    )

    net = build_mln().init()
    net.fit(X[:8], Y[:8])
    net.fit(X[:8], Y[:8])
    p_new = str(tmp_path / "with_ts.zip")
    ts = dict(net.training_state(), epoch=1,
              iterator_state={"cursor": 2})
    ModelSerializer.write_model(net, p_new, training_state=ts)
    got = read_training_state(p_new)
    assert got["iteration"] == 2
    assert got["epoch"] == 1
    assert got["iterator_state"] == {"cursor": 2}
    assert got["rng"] == np.asarray(net._rng, np.uint32).tolist()
    fresh = build_mln()
    loaded_ts = ModelSerializer.load_into(fresh, p_new)
    assert loaded_ts["iterator_state"] == {"cursor": 2}
    assert fresh.iteration == 2
    assert np.array_equal(np.asarray(fresh._rng), np.asarray(net._rng))
    assert params_equal(fresh.params, net.params)

    # old-format zip (no training_state entry) still loads
    p_old = str(tmp_path / "old.zip")
    ModelSerializer.write_model(net, p_old)
    with zipfile.ZipFile(p_old) as z:
        assert "training_state.json" not in z.namelist()
    assert read_training_state(p_old) is None
    restored = ModelSerializer.restore_multi_layer_network(p_old)
    assert params_equal(restored.params, net.params)


def test_load_into_rejects_wrong_class(tmp_path):
    from deeplearning4j_tpu.utils.serialization import ModelSerializer

    net = build_mln().init()
    p = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, p)
    with pytest.raises(ValueError, match="not ComputationGraph"):
        ModelSerializer.load_into(build_cg(), p)


def test_early_stopping_savers_atomic_and_managed(tmp_path):
    """Satellite: savers route through the resilience plane — atomic
    best/latest files, and the managed saver's digested latest chain."""
    from deeplearning4j_tpu.earlystopping.savers import (
        CheckpointManagerSaver,
        LocalFileModelSaver,
    )

    net = build_mln().init()
    net.fit(X[:8], Y[:8])
    saver = LocalFileModelSaver(str(tmp_path / "lfs"))
    saver.save_best_model(net, 0.5)
    best = saver.get_best_model()
    assert params_equal(best.params, net.params)
    assert not [f for f in os.listdir(str(tmp_path / "lfs"))
                if ".tmp" in f], "tmp files must not survive a save"

    managed = CheckpointManagerSaver(str(tmp_path / "managed"))
    managed.save_latest_model(net, 0.5)
    net.fit(X[:8], Y[:8])
    managed.save_latest_model(net, 0.4)
    managed.save_best_model(net, 0.4)
    latest = managed.get_latest_model()
    assert params_equal(latest.params, net.params)
    assert latest.iteration == net.iteration
    managed.manager.close()

    # restart continuity: a NEW saver over the same directory continues
    # the step chain — its first save must become the latest, not fall
    # below the retention keep-set and vanish
    managed2 = CheckpointManagerSaver(str(tmp_path / "managed"))
    net.fit(X[:8], Y[:8])
    managed2.save_latest_model(net, 0.3)
    latest2 = managed2.get_latest_model()
    assert latest2.iteration == net.iteration
    assert params_equal(latest2.params, net.params)
    managed2.manager.close()


# -------------------------------------------------------------- preemption
def _run_worker(mode, ckpt, out, kill=None, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    if kill:
        env["RES_KILL_STEP"] = str(kill)
    else:
        env.pop("RES_KILL_STEP", None)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "resilience_worker.py"),
         mode, ckpt, out],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_sigterm_preemption_checkpoint_and_reexec_resume(tmp_path):
    """Satellite: SIGTERM mid-fit in a real subprocess -> the goodbye
    checkpoint lands, re-exec resumes, final params equal the
    uninterrupted run (bit-exact) and no step is recomputed."""
    ckpt = str(tmp_path / "ckpt")
    r1 = _run_worker("train", ckpt, str(tmp_path / "killed.npz"), kill=7)
    assert r1.returncode == 143, (r1.stdout, r1.stderr)
    assert "PREEMPTED step=7" in r1.stdout
    assert any(n.startswith("ckpt-") for n in os.listdir(ckpt))

    r2 = _run_worker("train", ckpt, str(tmp_path / "resumed.npz"))
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    r3 = _run_worker("baseline", str(tmp_path / "nockpt"),
                     str(tmp_path / "base.npz"))
    assert r3.returncode == 0, (r3.stdout, r3.stderr)

    killed = np.load(str(tmp_path / "killed.npz"))
    resumed = np.load(str(tmp_path / "resumed.npz"))
    base = np.load(str(tmp_path / "base.npz"))
    # the goodbye checkpoint was taken AT the preemption step: resume
    # starts exactly there — zero lost work, zero recomputation
    assert int(resumed["resumed"]) == 7
    stitched = np.concatenate([killed["losses"][:7], resumed["losses"]])
    assert np.array_equal(stitched, base["losses"])
    pkeys = sorted(k for k in base.files if k.startswith("p"))
    for k in pkeys:
        assert np.array_equal(resumed[k], base[k]), k


def test_sigterm_handler_restored_after_fit():
    before = signal.getsignal(signal.SIGTERM)
    trainer = ResilientTrainer(build_mln())
    trainer.fit(mk_iterator(), num_epochs=1)
    assert signal.getsignal(signal.SIGTERM) is before
