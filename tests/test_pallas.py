"""Pallas kernel tests (interpret mode on CPU): the fused LSTM scan must
match the lax.scan reference bit-for-tolerance in forward AND gradient
(the same oracle pattern as the reference's cuDNN-vs-builtin layer tests)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.ops import pallas_kernels as pk


def make_inputs(n=4, t=6, h=8, seed=0):
    rng = np.random.default_rng(seed)
    xproj = rng.normal(0, 0.5, (n, t, 4 * h)).astype(np.float32)
    u = rng.normal(0, 0.3, (h, 4 * h)).astype(np.float32)
    p = rng.normal(0, 0.1, (3, h)).astype(np.float32)
    h0 = rng.normal(0, 0.2, (n, h)).astype(np.float32)
    c0 = rng.normal(0, 0.2, (n, h)).astype(np.float32)
    return map(jnp.asarray, (xproj, u, p, h0, c0))


class TestLstmPallas:
    def test_forward_matches_scan(self):
        xproj, u, p, h0, c0 = make_inputs()
        hs_k, hf_k, cf_k = pk.lstm_pallas_scan(xproj, u, p, h0, c0, True)
        hs_r, hf_r, cf_r = pk._lstm_scan_reference(xproj, u, p, h0, c0)
        np.testing.assert_allclose(hs_k, hs_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(hf_k, hf_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(cf_k, cf_r, rtol=1e-5, atol=1e-6)

    def test_gradients_match_scan(self):
        xproj, u, p, h0, c0 = make_inputs(seed=3)

        def loss_kernel(xp, uu, pp, hh, cc):
            hs, hf, cf = pk.lstm_pallas_scan(xp, uu, pp, hh, cc, True)
            return jnp.sum(hs**2) + jnp.sum(hf * cf)

        def loss_ref(xp, uu, pp, hh, cc):
            hs, hf, cf = pk._lstm_scan_reference(xp, uu, pp, hh, cc)
            return jnp.sum(hs**2) + jnp.sum(hf * cf)

        gk = jax.grad(loss_kernel, argnums=(0, 1, 2, 3, 4))(xproj, u, p, h0, c0)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(xproj, u, p, h0, c0)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_single_timestep(self):
        xproj, u, p, h0, c0 = make_inputs(t=1)
        hs_k, hf_k, _ = pk.lstm_pallas_scan(xproj, u, p, h0, c0, True)
        np.testing.assert_allclose(np.asarray(hs_k)[:, 0], hf_k, rtol=1e-6)

    def test_vmem_budget_gate(self):
        assert pk.lstm_scan_fits(32, 128)
        assert not pk.lstm_scan_fits(4096, 4096)


class TestLayerIntegration:
    def test_graves_lstm_layer_uses_kernel_when_forced(self, monkeypatch):
        """Layer output with the pallas path (interpret) equals the scan
        path for identical params."""
        from deeplearning4j_tpu.nn.conf.layers import GravesLSTM
        from deeplearning4j_tpu.nn.layers.factory import create_layer

        conf = GravesLSTM(n_in=5, n_out=8, activation="tanh",
                          weight_init="xavier")
        impl = create_layer(conf)
        # t=8: the layer only engages the kernel for t >= 8 (recurrent.py)
        params, state, _ = impl.initialize(jax.random.PRNGKey(0), (8, 5))
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(3, 8, 5)).astype(np.float32)
        )
        ys_scan, st_scan = impl.apply(params, state, x)

        import deeplearning4j_tpu.ops.pallas_kernels as pk_mod

        monkeypatch.setattr(pk_mod, "pallas_enabled", lambda: True)
        # bypass the measured-win shape table too — this test forces the
        # kernel path regardless of what the committed artifact says
        monkeypatch.setattr(pk_mod, "lstm_kernel_wins",
                            lambda *a, **k: True)
        real = pk_mod.lstm_pallas_scan
        called = []

        def interp(xproj, u, p, h0, c0, interpret=False):
            called.append(True)
            return real(xproj, u, p, h0, c0, True)

        monkeypatch.setattr(pk_mod, "lstm_pallas_scan", interp)
        ys_pal, st_pal = impl.apply(params, state, x)
        assert called, "kernel path was not exercised (gate regression?)"
        np.testing.assert_allclose(ys_pal, ys_scan, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(st_pal["h"], st_scan["h"], rtol=1e-5,
                                   atol=1e-6)

    def test_gradients_multiblock_reverse(self):
        """t=64 -> several time chunks: exercises the reversed index maps,
        the VMEM dU/dp accumulation across grid steps, and the dh/dc carry
        across block boundaries in the backward kernel."""
        xproj, u, p, h0, c0 = make_inputs(t=64, seed=9)

        def loss_kernel(xp, uu, pp, hh, cc):
            hs, hf, cf = pk.lstm_pallas_scan(xp, uu, pp, hh, cc, True)
            return jnp.sum(hs**2) + jnp.sum(hf * cf)

        def loss_ref(xp, uu, pp, hh, cc):
            hs, hf, cf = pk._lstm_scan_reference(xp, uu, pp, hh, cc)
            return jnp.sum(hs**2) + jnp.sum(hf * cf)

        gk = jax.grad(loss_kernel, argnums=(0, 1, 2, 3, 4))(xproj, u, p, h0, c0)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(xproj, u, p, h0, c0)
        for a, b, name in zip(gk, gr, ("xproj", "u", "p", "h0", "c0")):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4,
                                       err_msg=f"grad d{name}")

    def test_bwd_unfit_falls_back_to_scan_vjp(self, monkeypatch):
        xproj, u, p, h0, c0 = make_inputs(seed=4)
        monkeypatch.setattr(pk, "lstm_bwd_fits", lambda *a, **k: False)

        def loss(xp):
            hs, hf, cf = pk.lstm_pallas_scan(xp, u, p, h0, c0, True)
            return jnp.sum(hs**2)

        def loss_ref(xp):
            hs, hf, cf = pk._lstm_scan_reference(xp, u, p, h0, c0)
            return jnp.sum(hs**2)

        np.testing.assert_allclose(jax.grad(loss)(xproj),
                                   jax.grad(loss_ref)(xproj),
                                   rtol=1e-4, atol=1e-5)
