"""NLP stack tests — mirrors the reference's nlp test strategy (SURVEY.md
section 4 "NLP corpus tests": train embeddings on a small corpus, assert
similarity sanity, e.g. Word2VecTests.java similarity("day","night") > x;
tokenizer/vocab/serializer unit tests)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BagOfWordsVectorizer,
    CollectionSentenceIterator,
    DefaultTokenizerFactory,
    Glove,
    NGramTokenizerFactory,
    ParagraphVectors,
    TfidfVectorizer,
    VocabConstructor,
    Word2Vec,
    build_huffman,
    load_word2vec,
    read_word_vectors,
    save_word2vec,
    write_word_vectors,
)
from deeplearning4j_tpu.nlp.text import common_preprocessor
from deeplearning4j_tpu.nlp.vocab import VocabWord


def make_corpus(n=300, seed=7):
    """Synthetic corpus with two topical clusters so that in-cluster words
    land nearer each other than cross-cluster."""
    rng = np.random.default_rng(seed)
    time_words = ["day", "night", "morning", "evening", "noon"]
    animal_words = ["cat", "dog", "bird", "fish", "horse"]
    sents = []
    for _ in range(n):
        if rng.random() < 0.5:
            w1, w2 = rng.choice(time_words, 2, replace=False)
            sents.append(f"the {w1} follows the {w2} in time always")
        else:
            w1, w2 = rng.choice(animal_words, 2, replace=False)
            sents.append(f"a {w1} chased a {w2} around the yard")
    return sents


class TestTokenizers:
    def test_default_tokenizer_preprocessing(self):
        tf = DefaultTokenizerFactory(common_preprocessor)
        assert tf.tokenize("Hello, World! 123") == ["hello", "world", "123"]

    def test_ngram_tokenizer(self):
        tf = NGramTokenizerFactory(min_n=1, max_n=2)
        toks = tf.tokenize("a b c")
        assert toks == ["a", "b", "c", "a b", "b c"]

    def test_sentence_iterator_reset_semantics(self):
        it = CollectionSentenceIterator(["s one", "s two"])
        assert list(it) == ["s one", "s two"]
        assert list(it) == ["s one", "s two"]  # re-iterable


class TestVocabHuffman:
    def test_vocab_indices_sorted_by_frequency(self):
        vocab = VocabConstructor(min_word_frequency=2).build(
            [["a", "a", "a", "b", "b", "c"], ["a", "b", "c"]]
        )
        assert vocab.num_words() == 3
        assert vocab.word_at_index(0) == "a"  # most frequent first
        assert vocab.word_frequency("a") == 4

    def test_min_word_frequency_filters(self):
        vocab = VocabConstructor(min_word_frequency=3).build(
            [["a", "a", "a", "b"], ["b", "c"]]
        )
        assert "c" not in vocab
        assert "a" in vocab

    def test_huffman_prefix_free_and_frequency_ordered(self):
        words = [VocabWord(word=f"w{i}", count=c, index=i)
                 for i, c in enumerate([100, 50, 20, 10, 5, 2, 1])]
        build_huffman(words)
        codes = ["".join(map(str, w.codes)) for w in words]
        # prefix-free
        for i, c1 in enumerate(codes):
            for j, c2 in enumerate(codes):
                if i != j:
                    assert not c2.startswith(c1)
        # most frequent word has the (joint-)shortest code
        assert len(codes[0]) == min(len(c) for c in codes)
        # points index internal nodes of syn1 (0..n-2)
        for w in words:
            assert len(w.points) == len(w.codes)
            assert all(0 <= p <= len(words) - 2 for p in w.points)


class TestWord2Vec:
    @pytest.fixture(scope="class")
    def trained(self):
        vec = Word2Vec(layer_size=32, window=3, min_word_frequency=1,
                       epochs=5, seed=42, batch_size=512, learning_rate=0.05)
        return vec.fit(make_corpus())

    def test_topical_similarity(self, trained):
        # in-cluster beats cross-cluster (Word2VecTests-style sanity)
        assert trained.similarity("day", "night") > trained.similarity("day", "cat")
        assert trained.similarity("cat", "dog") > trained.similarity("cat", "evening")

    def test_words_nearest(self, trained):
        near = trained.words_nearest("day", top_n=4)
        assert len(near) == 4 and "day" not in near
        # at least one fellow time-word in the top neighbors
        assert set(near) & {"night", "morning", "evening", "noon"}

    def test_get_word_vector_shape(self, trained):
        v = trained.get_word_vector("day")
        assert v.shape == (32,)
        assert trained.get_word_vector("zzz_missing") is None

    def test_negative_sampling_path(self):
        vec = Word2Vec(layer_size=16, window=3, epochs=3, seed=1,
                       negative=5, batch_size=256)
        vec.fit(make_corpus(n=120))
        assert vec.similarity("day", "night") > vec.similarity("day", "dog") - 0.5
        assert vec.lookup_table.syn1neg is not None

    def test_cbow_path(self):
        vec = Word2Vec(layer_size=16, window=3, epochs=3, seed=1,
                       use_cbow=True, batch_size=256)
        vec.fit(make_corpus(n=120))
        assert np.isfinite(vec.lookup_table.syn0).all()

    def test_subsampling_runs(self):
        vec = Word2Vec(layer_size=8, window=2, epochs=1, sampling=1e-3)
        vec.fit(make_corpus(n=60))
        assert np.isfinite(vec.lookup_table.syn0).all()

    def test_deterministic_given_seed(self):
        a = Word2Vec(layer_size=8, window=2, epochs=1, seed=9).fit(make_corpus(n=50))
        b = Word2Vec(layer_size=8, window=2, epochs=1, seed=9).fit(make_corpus(n=50))
        np.testing.assert_allclose(a.lookup_table.syn0, b.lookup_table.syn0,
                                   rtol=1e-6)


class TestSerializer:
    def test_text_roundtrip(self, tmp_path):
        vec = Word2Vec(layer_size=8, epochs=1).fit(make_corpus(n=40))
        p = str(tmp_path / "vectors.txt")
        write_word_vectors(vec, p)
        lt = read_word_vectors(p)
        for w in ["day", "cat", "the"]:
            np.testing.assert_allclose(
                lt.vector(w), vec.get_word_vector(w), rtol=1e-5
            )

    def test_full_model_roundtrip(self, tmp_path):
        vec = Word2Vec(layer_size=8, epochs=1, negative=3).fit(make_corpus(n=40))
        p = str(tmp_path / "w2v.zip")
        save_word2vec(vec, p)
        restored = load_word2vec(p)
        np.testing.assert_allclose(restored.lookup_table.syn0, vec.lookup_table.syn0)
        np.testing.assert_allclose(restored.lookup_table.syn1, vec.lookup_table.syn1)
        assert restored.vocab.num_words() == vec.vocab.num_words()
        w = vec.vocab.vocab_words()[0]
        rw = restored.vocab.word_for(w.word)
        assert rw.codes == w.codes and rw.points == w.points


class TestGlove:
    def test_glove_trains_and_loss_decreases(self):
        g = Glove(layer_size=16, epochs=8, window=5, seed=3, x_max=10.0)
        g.fit(make_corpus(n=200))
        assert g.losses[-1] < g.losses[0]
        assert g.similarity("day", "night") > g.similarity("day", "fish") - 0.5
        assert len(g.words_nearest("cat", 3)) == 3


class TestParagraphVectors:
    def test_dbow_labels(self):
        sents = make_corpus(n=80)
        labels = ["TIME" if ("day" in s or "night" in s or "noon" in s or
                             "morning" in s or "evening" in s) else "ANIMAL"
                  for s in sents]
        pv = ParagraphVectors(layer_size=16, epochs=3, seed=5, batch_size=256)
        pv.fit_labelled(sents, labels)
        assert pv.doc_vector("TIME") is not None
        assert pv.doc_vector("ANIMAL") is not None
        assert np.isfinite(pv.doc_vectors).all()

    def test_dm_and_infer(self):
        sents = make_corpus(n=60)
        pv = ParagraphVectors(dm=True, layer_size=8, epochs=2, seed=5,
                              batch_size=128)
        pv.fit_labelled(sents)  # auto DOC_n labels
        v = pv.infer_vector("the day follows the night")
        assert v.shape == (8,)
        labels = pv.nearest_labels("the day follows the night", top_n=3)
        assert len(labels) == 3


class TestVectorizers:
    def test_bag_of_words(self):
        bow = BagOfWordsVectorizer().fit(["a b b c", "a c c d"])
        v = bow.transform("b b a")
        assert v[bow.vocab.index_of("b")] == 2.0
        assert v[bow.vocab.index_of("a")] == 1.0

    def test_tfidf_downweights_common(self):
        tf = TfidfVectorizer().fit(["a b", "a c", "a d"])
        v = tf.transform("a b")
        # 'a' appears in all docs -> idf 0; 'b' in one -> positive weight
        assert v[tf.vocab.index_of("a")] == pytest.approx(0.0)
        assert v[tf.vocab.index_of("b")] > 0

    def test_vectorize_dataset(self):
        bow = BagOfWordsVectorizer().fit(["good movie", "bad movie"])
        ds = bow.vectorize(["good movie", "bad movie"], ["pos", "neg"])
        assert ds.features.shape[0] == 2
        assert ds.labels.shape == (2, 2)
