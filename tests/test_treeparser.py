"""Tests for nlp/treeparser.py — constituency trees, PoS tagging, PCFG/CKY
parsing (reference text/corpora/treeparser/* + recursive/Tree.java)."""

import pytest

from deeplearning4j_tpu.nlp.treeparser import (
    AveragedPerceptronTagger,
    BinarizeTreeTransformer,
    CollapseUnaries,
    HeadWordFinder,
    Pcfg,
    Tree,
    TreeIterator,
    TreeParser,
    TreeVectorizer,
    parse_sexpr,
)

SEXPR = "(S (NP (DT the) (NN dog)) (VP (VBZ chases) (NP (DT the) (NN cat))))"


def test_sexpr_roundtrip():
    t = parse_sexpr(SEXPR)
    assert t.to_sexpr() == SEXPR
    assert t.yield_() == ["the", "dog", "chases", "the", "cat"]
    assert t.tokens == t.yield_()


def test_tree_structure_queries():
    t = parse_sexpr(SEXPR)
    assert t.label == "S"
    assert not t.is_leaf()
    assert t.depth() == 4  # S -> VP -> NP -> NN -> leaf
    np_node = t.first_child()
    assert np_node.label == "NP"
    dt = np_node.first_child()
    assert dt.is_preterminal()
    assert dt.first_child().is_leaf()
    assert len(t.leaves()) == 5
    assert len(t.preterminals()) == 5
    # parent links + ancestor
    assert dt.parent is np_node
    assert dt.ancestor(2) is t
    # clone is deep + equal by structure
    c = t.clone()
    assert c == t and c is not t
    c.first_child().label = "XP"
    assert c != t


def test_error_sum():
    t = parse_sexpr("(A (B b) (C c))")
    t.error = 1.0
    t.children[0].error = 2.0
    t.children[1].error = 0.5
    assert t.error_sum() == pytest.approx(3.5)


def test_binarize_and_unbinarize():
    t = parse_sexpr("(NP (DT the) (JJ big) (JJ red) (NN dog))")
    b = BinarizeTreeTransformer()
    bt = b.transform(t)
    for node in bt.subtrees():
        assert len(node.children) <= 2
    # yield preserved, and inverse recovers the original
    assert bt.yield_() == t.yield_()
    assert b.unbinarize(bt) == t


def test_collapse_unaries():
    t = parse_sexpr("(S (NP (NX (NN dog))) (VP (VBZ runs)))")
    ct = CollapseUnaries().transform(t)
    # NP->NX chain collapsed to NP over the preterminal
    assert ct.to_sexpr() == "(S (NP (NN dog)) (VP (VBZ runs)))"


def test_head_word_finder():
    t = parse_sexpr(SEXPR)
    h = HeadWordFinder()
    assert h.find_head(t).label == "VP"  # S -> VP
    np_node = t.first_child()
    assert h.find_head(np_node).label == "NN"  # NP -> NN
    assert h.head_word(t) == "chases"
    assert h.head_word(np_node) == "dog"
    h.annotate(t)
    assert t.head_word == "chases"


def test_rule_tagger_untrained():
    tags = AveragedPerceptronTagger().tag(
        ["The", "dog", "quickly", "jumped", "over", "3", "fences"]
    )
    assert tags[0] == "DT"
    assert tags[2] == "RB"
    assert tags[3] == "VBD"
    assert tags[5] == "CD"
    assert tags[6] == "NNS"


def test_perceptron_tagger_learns():
    corpus = [
        [("the", "DT"), ("dog", "NN"), ("barks", "VBZ")],
        [("a", "DT"), ("cat", "NN"), ("sleeps", "VBZ")],
        [("the", "DT"), ("cat", "NN"), ("barks", "VBZ")],
        [("dogs", "NNS"), ("bark", "VBP")],
        [("cats", "NNS"), ("sleep", "VBP")],
        [("the", "DT"), ("big", "JJ"), ("dog", "NN"), ("sleeps", "VBZ")],
        [("a", "DT"), ("small", "JJ"), ("cat", "NN"), ("runs", "VBZ")],
    ] * 3
    tagger = AveragedPerceptronTagger().train(corpus, iterations=8, seed=1)
    assert tagger.tag(["the", "dog", "sleeps"]) == ["DT", "NN", "VBZ"]
    assert tagger.tag(["a", "big", "cat", "barks"]) == ["DT", "JJ", "NN", "VBZ"]


def test_pcfg_cky_recovers_bracketing():
    bank = [
        parse_sexpr("(S (NP (DT the) (NN dog)) (VP (VBZ chases) (NP (DT the) (NN cat))))"),
        parse_sexpr("(S (NP (DT a) (NN cat)) (VP (VBZ sees) (NP (DT a) (NN bird))))"),
        parse_sexpr("(S (NP (DT the) (NN bird)) (VP (VBZ sings)))"),
    ]
    g = Pcfg.from_trees(bank)
    tree = g.parse(["DT", "NN", "VBZ", "DT", "NN"],
                   ["the", "fox", "chases", "a", "hen"])
    assert tree is not None
    assert tree.label == "S"
    assert tree.to_sexpr() == (
        "(S (NP (DT the) (NN fox)) (VP (VBZ chases) (NP (DT a) (NN hen))))"
    )
    # single-word VP from the third tree's unary-free binary shape
    t2 = g.parse(["DT", "NN", "VBZ"], ["a", "dog", "sings"])
    assert t2 is not None and t2.label == "S"


def test_treeparser_chunker_fallback():
    parser = TreeParser()
    trees = parser.get_trees("The big dog chased the cat. A bird sings.")
    assert len(trees) == 2
    t = trees[0]
    assert t.label == "S"
    labels = [c.label for c in t.children]
    assert "NP" in labels and "VP" in labels
    assert t.yield_()[:3] == ["The", "big", "dog"]


def test_treeparser_with_grammar():
    bank = [
        parse_sexpr("(S (NP (DT the) (NN dog)) (VP (VBZ chases) (NP (DT the) (NN cat))))"),
        parse_sexpr("(S (NP (DT a) (NN cat)) (VP (VBZ sees) (NP (DT a) (NN bird))))"),
    ]
    corpus = [
        [("the", "DT"), ("dog", "NN"), ("chases", "VBZ"), ("the", "DT"), ("cat", "NN")],
        [("a", "DT"), ("cat", "NN"), ("sees", "VBZ"), ("a", "DT"), ("bird", "NN")],
    ] * 4
    tagger = AveragedPerceptronTagger().train(corpus, iterations=6)
    parser = TreeParser(tagger=tagger).fit_grammar(bank)
    trees = parser.get_trees("the dog sees the bird.")
    assert len(trees) == 1
    assert trees[0].label == "S"
    assert trees[0].first_child().label == "NP"


def test_tree_vectorizer_labels():
    v = TreeVectorizer()
    trees = v.get_trees_with_labels("The dog runs.", "pos", ["NEG", "POS"])
    assert len(trees) == 1
    for node in trees[0].subtrees():
        assert node.gold_label == 1


def test_tree_iterator_batches():
    docs = [("The dog runs. The cat sleeps.", "POS"), ("A bird sings.", "NEG")]
    it = TreeIterator(docs, ["NEG", "POS"], batch_size=2)
    batches = list(it)
    total = sum(len(b) for b in batches)
    assert total == 3
    assert all(len(b) <= 2 for b in batches)
    first = batches[0][0]
    assert first.gold_label == 1


def test_pos_filter_tokenizer():
    from deeplearning4j_tpu.nlp.text import PosFilterTokenizerFactory

    tf = PosFilterTokenizerFactory(["NN", "NNS"])
    toks = tf.tokenize("the dog chased cats")
    assert toks == ["NONE", "dog", "NONE", "cats"]
    tf_drop = PosFilterTokenizerFactory(["NN", "NNS"], drop=True)
    assert tf_drop.tokenize("the dog chased cats") == ["dog", "cats"]
