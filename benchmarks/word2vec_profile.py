#!/usr/bin/env python
"""Word2Vec SGNS device profile: is the epoch scan scatter-bound?

VERDICT round-2 next-step #8 / SURVEY section 7 (round-1 item 9b): the
planned Pallas scatter-add kernel for sparse embedding rows should be
built ONLY if the profile shows the `.at[].add()` scatters dominating the
step; otherwise record the ruling-out. This script measures, on the real
chip, an attribution breakdown of one SGNS minibatch step
(nlp/word2vec.py:_neg_body — gathers, sigmoid math, two scatter-adds):

  full_ms         the real body (gathers + math + scatters)
  no_scatter_ms   ablation: scatters replaced by mathematically-comparable
                  dense reductions feeding the output (keeps the gathers +
                  einsum math; removes only the scatter HLOs)
  gather_ms       gathers alone (rows summed into the output)

scatter cost ~= full - no_scatter. The ablations are PROFILING-ONLY copies
of the body's math (cited inline); the training path is untouched.

Writes W2V_PROFILE.json and a verdict row into PALLAS_BENCH.json
("word2vec"."scatter_profile") so the decision is a committed artifact.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# imported at process start so round_guard.START_TS captures THIS
# process's birth time — the stale-round guards compare it to the
# watcher's .bench_round_start marker. round_guard (not bench!) so this
# profiler stops inheriting bench's import-time env mutations (ADVICE r5).
import round_guard as _round_guard

# fast-abort guard: a zombie watcher from a previous round retries this
# profile 3x per re-arm with a 1800s timeout each — it must die HERE, at
# process start, not after burning 30 min of the 1-core host per attempt.
# The catch is the spawner-identity signal (BENCH_WATCH_ROUND env vs the
# current marker mtime): a fresh child's own birth time is always newer
# than the marker, so only the inherited identity can expose a zombie
# spawner. (The write-time guard below still covers a round boundary
# that happens mid-profile.)
if _round_guard.round_is_stale():
    print("round marker is newer than this process; stale-round w2v "
          "profile aborting at startup", file=sys.stderr)
    raise SystemExit(3)

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import word2vec as w2v


def _force(x):
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(leaf.reshape(-1)[:1])


def _bench(fn, args, steps=40):
    out = fn(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _force(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main(vocab=50_000, dim=128, batch=2048, k=5):
    rng = np.random.default_rng(0)
    syn0 = jnp.asarray(rng.standard_normal((vocab, dim)) * 0.1, jnp.float32)
    syn1 = jnp.asarray(rng.standard_normal((vocab, dim)) * 0.1, jnp.float32)
    contexts = jnp.asarray(rng.integers(0, vocab, (batch,)), jnp.int32)
    targets = jnp.asarray(rng.integers(0, vocab, (batch, k + 1)), jnp.int32)
    labels = jnp.zeros((batch, k + 1), jnp.float32).at[:, 0].set(1.0)
    live = jnp.ones((batch, k + 1), jnp.float32)
    alpha = jnp.asarray(0.025, jnp.float32)

    full = jax.jit(w2v._neg_body)

    def no_scatter(syn0, syn1neg, contexts, targets, labels, live, alpha):
        # PROFILING ABLATION of nlp/word2vec.py:_neg_body — identical
        # gathers + einsum/sigmoid math; the two .at[].add scatters are
        # replaced by dense sums so the update math still runs and feeds
        # the output, but no scatter HLO is emitted.
        l1 = syn0[contexts]
        s1 = syn1neg[targets]
        dot = jnp.einsum("bd,bkd->bk", l1, s1)
        f = jax.nn.sigmoid(dot)
        base = jnp.where(dot > w2v.MAX_EXP, labels - 1.0,
                         jnp.where(dot < -w2v.MAX_EXP, labels, labels - f))
        g = base * alpha * live
        neu1e = jnp.einsum("bk,bkd->bd", g, s1)
        upd1 = (g[..., None] * l1[:, None, :]).sum(axis=(0, 1))  # (D,)
        upd0 = neu1e.sum(axis=0)                                  # (D,)
        return syn0 + upd0[None, :], syn1neg + upd1[None, :]

    def gathers_only(syn0, syn1neg, contexts, targets, *_):
        l1 = syn0[contexts]
        s1 = syn1neg[targets]
        return l1.sum(), s1.sum()

    args = (syn0, syn1, contexts, targets, labels, live, alpha)
    res = {
        "vocab": vocab, "dim": dim, "batch": batch, "negatives": k,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "full_ms": round(_bench(full, args), 3),
        "no_scatter_ms": round(_bench(jax.jit(no_scatter), args), 3),
        "gather_ms": round(_bench(jax.jit(gathers_only), args), 3),
    }
    scatter_ms = max(0.0, res["full_ms"] - res["no_scatter_ms"])
    res["scatter_ms_attributed"] = round(scatter_ms, 3)
    res["scatter_fraction"] = round(scatter_ms / max(res["full_ms"], 1e-9),
                                    3)
    if res["scatter_fraction"] >= 0.4:
        res["verdict"] = (
            "SCATTER-BOUND: the .at[].add scatters cost "
            f"{res['scatter_fraction']:.0%} of the step — a pallas "
            "row-scatter-add kernel is justified (SURVEY section 7 item 9b)")
    else:
        res["verdict"] = (
            f"NOT scatter-bound ({res['scatter_fraction']:.0%} of the "
            "step): the pallas scatter-add kernel is ruled out by "
            "measurement; gathers+math dominate and already ride XLA")
    # stale-round guard (same second-line defense as bench._persist_partial):
    # a profile child that survived a round-boundary plain kill must not
    # re-create the NEW round's W2V_PROFILE.json from old-round code — the
    # watcher's [ ! -f ] gate would then skip profiling and declare the
    # capture complete on a stale artifact
    if _round_guard.round_is_stale():
        print("round marker is newer than this process; refusing to write "
              "stale W2V_PROFILE.json", file=sys.stderr)
        raise SystemExit(3)
    # atomic write: a timeout kill mid-dump must not leave a truncated
    # artifact that the watcher's existence check would count as success
    with open("W2V_PROFILE.json.tmp", "w") as f:
        json.dump(res, f, indent=1)
    os.replace("W2V_PROFILE.json.tmp", "W2V_PROFILE.json")
    from deeplearning4j_tpu.ops.kernel_gate import record_win

    record_win("word2vec", "scatter_profile", res)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
