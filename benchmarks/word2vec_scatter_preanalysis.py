"""Word2Vec scatter-add pre-analysis — CPU-labeled, NON-CHIP numbers.

VERDICT r5 ask #7: the on-chip scatter profile (`benchmarks/
word2vec_profile.py` -> W2V_PROFILE.json) has been armed since round 3
but needs the tunnel; this pre-analysis bounds the question NOW on CPU so
the round the profile lands, the kernel decision is one step, not two.

The question (open since round 1): in the SGNS step (`nlp/word2vec.py
_neg_body` — the jitted redesign of SkipGram.java:214-252's Hogwild
updates), can the two `.at[].add()` scatter-adds into syn0/syn1neg come
to DOMINATE at reference-scale vocabularies (text8: ~71k words at
min_count 5, ~253k unfiltered), justifying a Pallas scatter kernel?

Method (all on forced-CPU jax, interpret-grade evidence only):
  * time the FULL jitted `_neg_body` per vocab size;
  * time a MATH-ONLY variant (identical gathers/sigmoid/einsum math,
    returns the dense update tensors instead of scattering them);
  * time a SCATTER-ONLY jit (the `_mean_scale` count scatter + the two
    row scatter-adds, on precomputed updates);
  * scatter_fraction = 1 - math_only/full  (plus the direct scatter
    timing as a cross-check).

Analytic bound (vocab-independence argument): the scatter's write set is
B*(K+2) rows x D floats REGARDLESS of V — growing the vocab only grows
the TABLE the rows land in (cache pressure on CPU, HBM paging on TPU),
not the update volume. So the scatter fraction is bounded by row-update
traffic vs the gather+einsum math on the same rows, and a vocab sweep
measures pure locality effects. Whatever this says, the DECISION stays
pending the on-chip profile: TPU scatter cost is dominated by dynamic
-update-slice serialization, which CPU numbers cannot see (hence the
loud non-chip label on the artifact).

Writes W2V_SCATTER_PREANALYSIS.json; run from the repo root:
    python benchmarks/word2vec_scatter_preanalysis.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # NEVER touch the tunnel here

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.nlp.word2vec import (  # noqa: E402
    MAX_EXP,
    _mean_scale,
    _neg_body,
)

ARTIFACT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "W2V_SCATTER_PREANALYSIS.json")


def _math_only(syn0, syn1neg, contexts, targets, labels, live, alpha):
    """_neg_body with the scatters REMOVED: identical gathers + sigmoid
    math + einsums, returning the dense per-pair updates instead of
    applying them (kept in lockstep with nlp/word2vec._neg_body:92-116 —
    if the step changes, re-derive this)."""
    l1 = syn0[contexts]
    s1 = syn1neg[targets]
    dot = jnp.einsum("bd,bkd->bk", l1, s1)
    f = jax.nn.sigmoid(dot)
    base = jnp.where(
        dot > MAX_EXP, labels - 1.0,
        jnp.where(dot < -MAX_EXP, labels, labels - f))
    g = base * alpha * live
    neu1e = jnp.einsum("bk,bkd->bd", g, s1)
    return g[..., None] * l1[:, None, :], neu1e


def _scatter_only(syn0, syn1neg, contexts, targets, upd_t, neu1e, live):
    """Just the scatter side: the _mean_scale count scatters + the two
    row scatter-adds, on precomputed update tensors."""
    t_scale = _mean_scale(syn1neg.shape[0], targets, live)
    syn1neg = syn1neg.at[targets].add(t_scale[..., None] * upd_t)
    ctx_live = (live.sum(axis=1) > 0).astype(jnp.float32)
    ctx_scale = _mean_scale(syn0.shape[0], contexts, ctx_live)
    syn0 = syn0.at[contexts].add(ctx_scale[:, None] * neu1e)
    return syn0, syn1neg


def _time(fn, args, reps=5):
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: np.asarray(a.reshape(-1)[:1]), out)  # force
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.tree_util.tree_map(lambda a: np.asarray(a.reshape(-1)[:1]), out)
    return (time.perf_counter() - t0) / reps


def _time_donated(fn, tables, rest, reps=5):
    """Time a table-mutating step under the PRODUCTION calling convention:
    syn0/syn1 donated and re-bound each call (nlp/word2vec.py's
    donate_argnums=(0, 1) discipline). Without donation each call COPIES
    both V x D tables, and the 'scatter cost' reads as a table-sized
    memcpy that scales with V — the first (wrong) version of this script
    measured exactly that artifact: 77->99% 'scatter fraction' that was
    really copy fraction."""
    tables = fn(*tables, *rest)  # warm/compile; re-bind donated buffers
    jax.tree_util.tree_map(lambda a: np.asarray(a.reshape(-1)[:1]), tables)
    t0 = time.perf_counter()
    for _ in range(reps):
        tables = fn(*tables, *rest)
    jax.tree_util.tree_map(lambda a: np.asarray(a.reshape(-1)[:1]), tables)
    return (time.perf_counter() - t0) / reps


def run(vocab_sizes=(10_000, 71_000, 253_000), batch=2048, k_neg=5,
        dim=128, reps=5):
    rng = np.random.default_rng(0)
    rows = []
    for v in vocab_sizes:
        syn0 = jnp.asarray(rng.standard_normal((v, dim)), jnp.float32)
        syn1 = jnp.asarray(rng.standard_normal((v, dim)), jnp.float32)
        contexts = jnp.asarray(rng.integers(0, v, batch), jnp.int32)
        targets = jnp.asarray(rng.integers(0, v, (batch, k_neg + 1)),
                              jnp.int32)
        labels = jnp.zeros((batch, k_neg + 1),
                           jnp.float32).at[:, 0].set(1.0)
        live = jnp.ones((batch, k_neg + 1), jnp.float32)
        alpha = jnp.asarray(0.025, jnp.float32)

        # donation matches production (word2vec.py donate_argnums=(0,1)):
        # the tables update in place; un-donated timing would measure a
        # V-scaled table memcpy instead of the scatter
        # graftlint: disable-file=donation-through-dispatch -- this pre-analysis bench deliberately measures the production donation contract (word2vec.py donate_argnums=(0,1)); tables are rebuilt between legs
        full = jax.jit(_neg_body, donate_argnums=(0, 1))
        math = jax.jit(_math_only)
        scat = jax.jit(_scatter_only, donate_argnums=(0, 1))

        t_full = _time_donated(full, (syn0, syn1),
                               (contexts, targets, labels, live, alpha),
                               reps)
        syn0 = jnp.asarray(rng.standard_normal((v, dim)), jnp.float32)
        syn1 = jnp.asarray(rng.standard_normal((v, dim)), jnp.float32)
        t_math = _time(math, (syn0, syn1, contexts, targets, labels, live,
                              alpha), reps)
        upd_t, neu1e = math(syn0, syn1, contexts, targets, labels, live,
                            alpha)
        t_scat = _time_donated(scat, (syn0, syn1),
                               (contexts, targets, upd_t, neu1e, live),
                               reps)
        rows.append({
            "vocab": v, "batch": batch, "negative_k": k_neg, "dim": dim,
            "full_step_ms": round(t_full * 1e3, 3),
            "math_only_ms": round(t_math * 1e3, 3),
            "scatter_only_ms": round(t_scat * 1e3, 3),
            "scatter_fraction_subtractive": round(
                max(0.0, 1.0 - t_math / t_full), 4),
            "scatter_fraction_direct": round(t_scat / t_full, 4),
        })
        print(f"V={v}: full {t_full*1e3:.2f}ms, math {t_math*1e3:.2f}ms, "
              f"scatter {t_scat*1e3:.2f}ms "
              f"(fraction ~{1 - t_math / t_full:.0%})", flush=True)
    return rows


def main():
    rows = run()
    fr = [r["scatter_fraction_subtractive"] for r in rows]
    artifact = {
        "label": "PRE-ANALYSIS on forced-CPU jax — NOT on-chip evidence; "
                 "the kernel decision stays pending W2V_PROFILE.json",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "generated": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "rows": rows,
        "analysis": {
            "write_volume_vocab_independent": True,
            "note": "scatter writes B*(K+2) rows x D floats regardless of "
                    "V; the vocab sweep isolates table-locality effects. "
                    "On TPU the analogous cost is scatter serialization in "
                    "HBM, invisible to CPU timing — on-chip profile "
                    "required before any kernel work.",
            "cpu_scatter_fraction_range": [min(fr), max(fr)],
        },
    }
    with open(ARTIFACT, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {ARTIFACT}")
    return artifact


if __name__ == "__main__":
    main()
