#!/usr/bin/env python
"""Prove-or-drop benchmark: fused Pallas LSTM scan vs XLA lax.scan on the
real chip (VERDICT round-1 item 9). Writes PALLAS_BENCH.json.

Round-1 measurement (recorded in ops/pallas_kernels.py docstring): XLA's
scan runs the recurrence fully pipelined at ~peak MXU throughput and beats
the hand kernel by ~100x — this script reproduces that result so the
decision is backed by a committed artifact, per the project rule "let XLA
fuse — don't hand-schedule what the compiler already does". The kernel
stays opt-in (DL4J_TPU_PALLAS=1) as the selectable-backend slot mirroring
the reference's reflective cuDNN helper loading
(ConvolutionLayer.java:64-70).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import pallas_kernels as pk


def _bench(fn, args, steps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main():
    backend = jax.default_backend()
    results = {"backend": backend, "cases": []}
    rng = np.random.default_rng(0)
    for n, t, h in ((32, 128, 128), (64, 256, 256)):
        xproj = jnp.asarray(rng.standard_normal((n, t, 4 * h)), jnp.float32)
        u = jnp.asarray(rng.standard_normal((h, 4 * h)) * 0.05, jnp.float32)
        p = jnp.zeros((3, h), jnp.float32)
        h0 = jnp.zeros((n, h), jnp.float32)
        c0 = jnp.zeros((n, h), jnp.float32)

        scan_fn = jax.jit(pk._lstm_scan_reference)
        scan_ms = _bench(scan_fn, (xproj, u, p, h0, c0)) * 1e3

        interpret = backend != "tpu"
        pallas_fn = jax.jit(
            lambda *a: pk.lstm_pallas_scan(*a, interpret)
        )
        try:
            pallas_ms = _bench(pallas_fn, (xproj, u, p, h0, c0),
                               steps=3 if interpret else 20) * 1e3
        except Exception as e:  # noqa: BLE001
            pallas_ms = None
            results["cases"].append(
                {"n": n, "t": t, "h": h, "scan_ms": round(scan_ms, 3),
                 "pallas_error": f"{type(e).__name__}: {e}"}
            )
            continue
        results["cases"].append(
            {
                "n": n, "t": t, "h": h,
                "scan_ms": round(scan_ms, 3),
                "pallas_ms": round(pallas_ms, 3),
                "pallas_interpret_mode": interpret,
                "scan_speedup_over_pallas": round(pallas_ms / scan_ms, 2),
            }
        )
    results["verdict"] = (
        "lax.scan wins on TPU; pallas kernel stays OPT-IN "
        "(DL4J_TPU_PALLAS=1) as the selectable-backend pattern"
        if backend == "tpu"
        else "CPU run (interpret mode) — timing not meaningful; see TPU run"
    )
    with open("PALLAS_BENCH.json", "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
