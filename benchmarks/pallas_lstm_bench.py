#!/usr/bin/env python
"""Prove-or-drop benchmark: fused Pallas LSTM scan vs XLA lax.scan on the
real chip (VERDICT round-1 item 9).

Methodology: each (N, T, H) case times 60 jitted calls per implementation,
fenced by a one-element host readback with a true data dependency
(jax.block_until_ready does NOT fence remote execution through the axon
tunnel — round-1's "scan wins ~100x" was that artifact), and asserts
on-chip numerical equivalence between kernel and scan before recording.
The measured verdict — written to PALLAS_BENCH.json, the single source of
truth — drives whether the kernel stays default-on for TPU
(ops/pallas_kernels.py pallas_enabled; DL4J_TPU_PALLAS=0 disables). This
is the selectable-backend slot mirroring the reference's reflective cuDNN
helper loading (ConvolutionLayer.java:64-70).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import pallas_kernels as pk


def _force(x):
    """Sound completion fence: block_until_ready does not reliably wait for
    remote execution through the axon tunnel; a one-element host readback
    with a true data dependency does."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(leaf.reshape(-1)[:1])


def _bench(fn, args, steps=60):
    out = fn(*args)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    _force(out)
    return (time.perf_counter() - t0) / steps


def main():
    backend = jax.default_backend()
    # the axon remote plugin IS a TPU — compile pallas for real there
    is_tpu = backend == "tpu" or jax.devices()[0].platform in ("tpu", "axon")
    results = {"backend": backend, "cases": []}
    rng = np.random.default_rng(0)
    for n, t, h in ((32, 128, 128), (64, 256, 256), (128, 512, 512)):
        xproj = jnp.asarray(rng.standard_normal((n, t, 4 * h)), jnp.float32)
        u = jnp.asarray(rng.standard_normal((h, 4 * h)) * 0.05, jnp.float32)
        p = jnp.zeros((3, h), jnp.float32)
        h0 = jnp.zeros((n, h), jnp.float32)
        c0 = jnp.zeros((n, h), jnp.float32)

        scan_fn = jax.jit(pk._lstm_scan_reference)
        scan_ms = _bench(scan_fn, (xproj, u, p, h0, c0)) * 1e3
        scan_out = scan_fn(xproj, u, p, h0, c0)

        interpret = not is_tpu
        pallas_fn = jax.jit(
            lambda *a: pk.lstm_pallas_scan(*a, interpret)
        )
        try:
            pallas_ms = _bench(pallas_fn, (xproj, u, p, h0, c0),
                               steps=3 if interpret else 60) * 1e3
        except Exception as e:  # noqa: BLE001
            pallas_ms = None
            results["cases"].append(
                {"n": n, "t": t, "h": h, "scan_ms": round(scan_ms, 3),
                 "pallas_error": f"{type(e).__name__}: {e}"}
            )
            continue
        # on-chip numerical equivalence: the kernel must match the scan
        pal_out = pallas_fn(xproj, u, p, h0, c0)
        max_dev = max(
            float(jnp.max(jnp.abs(a - b)))
            for a, b in zip(pal_out, scan_out)
        )
        if max_dev >= 1e-4:
            results["cases"].append(
                {"n": n, "t": t, "h": h, "scan_ms": round(scan_ms, 3),
                 "pallas_error": f"DIVERGENCE vs scan: max_abs_dev={max_dev}"}
            )
            continue
        case = {
            "n": n, "t": t, "h": h,
            "scan_ms": round(scan_ms, 3),
            "pallas_ms": round(pallas_ms, 3),
            "pallas_interpret_mode": interpret,
            "scan_speedup_over_pallas": round(pallas_ms / scan_ms, 2),
            "max_abs_dev_vs_scan": max_dev,
        }

        # fwd+bwd (the training step shape): reverse-time pallas backward
        # kernel vs scan autodiff. Interpret mode (CPU smoke) only runs the
        # smallest case — interpreted reverse sweeps on the big shapes take
        # tens of minutes and the unit tests already cover correctness.
        if interpret and (n, t, h) != (32, 128, 128):
            results["cases"].append(case)
            continue

        def grad_of(fn):
            return jax.jit(jax.grad(
                lambda xp, uu: jnp.sum(fn(xp, uu, p, h0, c0)[0] ** 2),
                argnums=(0, 1)))

        scan_g = grad_of(lambda *a: pk._lstm_scan_reference(*a))
        pallas_g = grad_of(lambda *a: pk.lstm_pallas_scan(*a, interpret))
        try:
            scan_bwd_ms = _bench(scan_g, (xproj, u),
                                 steps=3 if interpret else 30) * 1e3
            pallas_bwd_ms = _bench(pallas_g, (xproj, u),
                                   steps=3 if interpret else 30) * 1e3
            g_dev = max(
                float(jnp.max(jnp.abs(a - b)))
                for a, b in zip(pallas_g(xproj, u), scan_g(xproj, u))
            )
            case.update({
                "scan_fwdbwd_ms": round(scan_bwd_ms, 3),
                "pallas_fwdbwd_ms": round(pallas_bwd_ms, 3),
                "bwd_kernel_engaged": pk.lstm_bwd_fits(n, h, t),
                "scan_speedup_over_pallas_fwdbwd":
                    round(pallas_bwd_ms / scan_bwd_ms, 2),
                "max_grad_dev_vs_scan": g_dev,
            })
        except Exception as e:  # noqa: BLE001
            case["bwd_error"] = f"{type(e).__name__}: {e}"
        results["cases"].append(case)
    if not is_tpu:
        results["verdict"] = (
            "CPU run (interpret mode) — timing not meaningful; see TPU run"
        )
    else:
        ratios = [c["scan_speedup_over_pallas"] for c in results["cases"]
                  if "pallas_ms" in c]
        wins = sum(1 for r in ratios if r > 1.0)  # >1 = scan faster
        if ratios and wins == 0:
            results["verdict"] = (
                "fused Pallas LSTM beats lax.scan on every measured shape ("
                + ", ".join(f"{1/r:.2f}x" for r in ratios)
                + ") — round-1's 'scan wins ~100x' was an artifact of the "
                "unsound block_until_ready fence through the remote tunnel; "
                "kernel is DEFAULT ON for TPU (DL4J_TPU_PALLAS=0 disables)"
            )
        elif ratios and wins == len(ratios):
            results["verdict"] = (
                "lax.scan beats the pallas kernel on every measured shape; "
                "set DL4J_TPU_PALLAS=0 to disable the default-on kernel"
            )
        else:
            results["verdict"] = (
                "parity within remote-tunnel timing noise (scan/pallas "
                "ratios: " + ", ".join(f"{r:.2f}" for r in ratios)
                + "); round-1's 'scan wins ~100x' was a fence artifact. "
                "The kernel is DEFAULT ON for TPU (DL4J_TPU_PALLAS=0 "
                "disables)"
            )
    # Merge into PALLAS_BENCH.json (never clobber other kernel groups —
    # the attention rows live in the same artifact) and emit the per-shape
    # win-table rows that ops/pallas_kernels.lstm_kernel_wins consults.
    # CPU/interpret smoke runs must NOT touch the artifact: they would
    # replace real-chip rows with timing-meaningless ones and silently
    # disable the kernel everywhere (the gate ignores non-chip rows, but
    # same-key overwrites would delete the chip evidence).
    if not is_tpu:
        print(json.dumps(results))
        return
    from deeplearning4j_tpu.ops.kernel_gate import record_win

    for c in results["cases"]:
        if "pallas_ms" not in c:
            continue
        row = {
            "n": c["n"], "t": c["t"], "h": c["h"],
            "speedup": round(c["scan_ms"] / c["pallas_ms"], 2),
            "scan_ms": c["scan_ms"], "pallas_ms": c["pallas_ms"],
            "backend": results["backend"],
            "interpret": c["pallas_interpret_mode"],
        }
        if "pallas_fwdbwd_ms" in c:
            row["fwdbwd_speedup"] = round(
                c["scan_fwdbwd_ms"] / c["pallas_fwdbwd_ms"], 2)
            row["scan_fwdbwd_ms"] = c["scan_fwdbwd_ms"]
            row["pallas_fwdbwd_ms"] = c["pallas_fwdbwd_ms"]
            row["bwd_kernel_engaged"] = c.get("bwd_kernel_engaged")
        record_win("lstm", f"n{c['n']}_t{c['t']}_h{c['h']}", row)
    # per-group verdict (PALLAS_BENCH.json "verdicts" dict) — the legacy
    # single top-level verdict got overwritten by whichever kernel bench
    # ran last across round-boundary archives
    from deeplearning4j_tpu.ops.kernel_gate import record_verdict

    record_verdict("lstm", results["verdict"])
    print(json.dumps(results))


if __name__ == "__main__":
    main()
